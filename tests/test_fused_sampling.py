"""Decode-fused sampling: token selection inside the jitted step program.

The fused decode window ships (B, n) token ids — plus an optional
(B, n, k) logprob sliver — back to the host instead of per-step (B, V)
logits. The unfused lane (forward-only program + host logits round-trip
+ separate sampling dispatch) stays wired as the measurement reference:
greedy output must be BIT-IDENTICAL fused vs unfused across every
scheduler (sync, pipelined, chunked prefill, prefix cache), and the
step-program output shapes must prove the host-transfer claim.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation import paged
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
DRAFT_CFG = dataclasses.replace(CFG, n_layers=1, d_model=16, n_heads=2)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)]))
        .tolist()
        for i in range(n)
    ]


ENGINE_CONFIGS = {
    "sync": dict(_pipeline=False, steps_per_sched=3),
    "sync_sps1": dict(_pipeline=False, steps_per_sched=1),
    "pipelined": dict(_pipeline=True, pipeline_depth=2, steps_per_sched=3),
    "pipelined_depth1": dict(_pipeline=True, pipeline_depth=1,
                             steps_per_sched=1),
    "chunked_prefill": dict(_pipeline=True, pipeline_depth=2,
                            steps_per_sched=3, prefill_chunk_tokens=8),
    "prefix_cache": dict(_pipeline=True, pipeline_depth=2,
                         steps_per_sched=3, prefix_cache=True),
}


def _run(params, prompts, n_new, *, fused, logprobs_k=0, **kw):
    kw = dict(kw)
    pipeline = kw.pop("_pipeline")
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=2, n_blocks=24,
        block_size=8, fused_sampling=fused, logprobs_k=logprobs_k, **kw,
    )
    for p in prompts:
        eng.submit(p, n_new)
    out = eng.run(pipeline=pipeline)
    return out, eng


@pytest.mark.parametrize("config", sorted(ENGINE_CONFIGS))
def test_fused_vs_unfused_greedy_bit_identity(params, config):
    """The tentpole contract: moving sampling into the step program must
    not move a single greedy token, under every scheduler — admission
    churn, chunked prefill, and prefix-cache reuse included."""
    prompts = _prompts(5)
    kw = ENGINE_CONFIGS[config]
    fused_out, fused_eng = _run(params, prompts, 9, fused=True, **kw)
    unfused_out, unfused_eng = _run(params, prompts, 9, fused=False, **kw)
    assert fused_out == unfused_out
    # The transfer claim, engine-side: only the unfused lane ever moves
    # (B, V) logits across the device boundary.
    assert fused_eng.stats["logits_bytes_host"] == 0
    assert unfused_eng.stats["logits_bytes_host"] > 0


def test_step_program_ships_tokens_not_logits(params):
    """Output-shape proof of the host-transfer claim: the fused window
    program returns (B, n) ids + (B, n, k) sliver; only the unfused
    forward returns (B, V) logits."""
    bs, n_blocks = 8, 16
    prompts = _prompts(2)
    pools = transformer.make_paged_kv_pool(CFG, n_blocks, bs, dtype="float32")
    alloc = paged.BlockAllocator(n_blocks)
    tables = np.zeros((2, 4), np.int32)
    seq = np.zeros((2,), np.int32)
    toks0 = np.zeros((2,), np.int32)
    for i, p in enumerate(prompts):
        ids = alloc.alloc(4)
        last, pools = paged.prefill_into_pool(
            params, CFG, pools, p, ids[: paged.required_blocks(len(p), bs)]
        )
        tables[i, : len(ids)] = ids
        seq[i] = len(p)
        toks0[i] = int(np.argmax(np.asarray(last)))
    b, v, n, k = 2, CFG.vocab_size, 4, 3
    args = (jnp.asarray(toks0), jnp.asarray(tables), jnp.asarray(seq))

    toks, lp_vals, lp_ids, pools = paged.paged_decode_steps_lp(
        params, pools, *args, jax.random.key(1), CFG, n, logprobs_k=k
    )
    assert toks.shape == (b, n) and toks.dtype == jnp.int32
    assert lp_vals.shape == (b, n, k) and lp_vals.dtype == jnp.float32
    assert lp_ids.shape == (b, n, k) and lp_ids.dtype == jnp.int32
    # Per-window host payload: ids + sliver vs n full logit planes.
    assert toks.size + lp_vals.size + lp_ids.size < b * n * v

    nxt, lv, li, pools = paged.paged_decode_step_lp(
        params, pools, jnp.asarray(toks)[:, -1], jnp.asarray(tables),
        jnp.asarray(seq) + n, jax.random.key(2), CFG, logprobs_k=k,
    )
    assert nxt.shape == (b,) and lv.shape == (b, k) and li.shape == (b, k)

    logits, pools = paged.paged_decode_logits(
        params, pools, nxt, jnp.asarray(tables), jnp.asarray(seq) + n + 1,
        CFG,
    )
    assert logits.shape == (b, v) and logits.dtype == jnp.float32
    # Greedy consistency between the lanes, same pool state.
    assert np.array_equal(
        np.asarray(paged.sample_tokens(logits, jax.random.key(3))),
        np.asarray(jnp.argmax(logits, axis=-1)),
    )


@pytest.mark.parametrize("config", ["sync", "pipelined", "chunked_prefill",
                                    "prefix_cache"])
def test_logprobs_sliver_alignment(params, config):
    """logprobs_k > 0: one entry per output token in order; prefill-
    sampled first tokens carry None (no sliver in prefill programs);
    every decode entry's top-1 id equals the emitted greedy token and
    its values are descending finite log-probabilities."""
    prompts = _prompts(4)
    n_new = 7
    out, eng = _run(params, prompts, n_new, fused=True, logprobs_k=3,
                    **ENGINE_CONFIGS[config])
    assert set(eng.logprobs) == set(out)
    for rid, toks in out.items():
        lps = eng.logprobs[rid]
        assert len(lps) == len(toks)
        assert lps[0] is None  # prefill-sampled first token
        for tok, entry in zip(toks[1:], lps[1:]):
            if entry is None:  # post-preemption restart slot
                continue
            vals, ids = entry
            assert len(vals) == 3 and len(ids) == 3
            assert ids[0] == tok  # greedy token IS the top-1 logprob id
            assert all(v <= 0.0 and np.isfinite(v) for v in vals)
            assert vals == sorted(vals, reverse=True)


def test_fused_sampling_validation(params):
    with pytest.raises(ValueError, match="logprobs_k"):
        ServingEngine(params, CFG, logprobs_k=-1)
    with pytest.raises(ValueError, match="fused_sampling"):
        ServingEngine(params, CFG, fused_sampling=False, logprobs_k=2)
    draft = transformer.init_params(DRAFT_CFG, jax.random.key(99))
    with pytest.raises(ValueError, match="fused decode path"):
        ServingEngine(
            params, CFG, fused_sampling=False, spec_k=2,
            draft_params=draft, draft_cfg=DRAFT_CFG,
        )
    with pytest.raises(ValueError, match="fused decode path"):
        ServingEngine(
            params, CFG, logprobs_k=1, spec_k=2,
            draft_params=draft, draft_cfg=DRAFT_CFG,
        )


def test_unfused_sampled_matches_fused_sampled_stream(params):
    """Beyond greedy: at temperature > 0 the two lanes share the key
    stream (sample_tokens is jit-boundary invariant), so sampled tokens
    are bit-identical too."""
    prompts = _prompts(3)
    kw = dict(steps_per_sched=3)
    outs = []
    for fused in (True, False):
        eng = ServingEngine(
            params, CFG, temperature=0.7, top_k=8, max_batch=2,
            n_blocks=24, block_size=8, fused_sampling=fused, seed=5, **kw,
        )
        for p in prompts:
            eng.submit(p, 6)
        outs.append(eng.run(pipeline=False))
    assert outs[0] == outs[1]
