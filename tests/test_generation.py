"""Generation: cached decode == uncached forward, sampling semantics, CLI path."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.generate import generate, load_model_for_inference
from pretraining_llm_tpu.generation.sampling import sample_logits
from pretraining_llm_tpu.models import transformer

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def test_unstacked_cache_layout_matches_stacked(params):
    """decode_cache_layout='unstacked' (per-layer caches, python layer
    loop, in-place carry updates) must generate EXACTLY the stacked
    layout's tokens — greedy, ragged rows, and int8 quantized."""
    cfg_u = dataclasses.replace(CFG, decode_cache_layout="unstacked")
    prompt = jax.random.randint(jax.random.key(11), (2, 9), 0, CFG.vocab_size)
    want = np.asarray(
        generate(params, CFG, prompt, 12, jax.random.key(3), temperature=0.0)
    )
    got = np.asarray(
        generate(params, cfg_u, prompt, 12, jax.random.key(3), temperature=0.0)
    )
    np.testing.assert_array_equal(got, want)

    # Ragged rows exercise the per-layer cache roll after prefill.
    lengths = np.asarray([5, 9], np.int32)
    want_r = np.asarray(generate(
        params, CFG, prompt, 8, jax.random.key(4), temperature=0.0,
        prompt_lengths=lengths,
    ))
    got_r = np.asarray(generate(
        params, cfg_u, prompt, 8, jax.random.key(4), temperature=0.0,
        prompt_lengths=lengths,
    ))
    np.testing.assert_array_equal(got_r, want_r)

    # int8 quantized cache leaves carry through the unstacked container.
    cfg8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
    cfg8_u = dataclasses.replace(cfg8, decode_cache_layout="unstacked")
    want_q = np.asarray(
        generate(params, cfg8, prompt, 8, jax.random.key(5), temperature=0.0)
    )
    got_q = np.asarray(
        generate(params, cfg8_u, prompt, 8, jax.random.key(5), temperature=0.0)
    )
    np.testing.assert_array_equal(got_q, want_q)


def test_greedy_cached_matches_uncached(params):
    """KV-cached greedy decode must equal argmax over full re-forwards
    (the reference's cache-less loop, transformer.py:96-114)."""
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, CFG.vocab_size)
    n_new = 10
    got = np.asarray(generate(params, CFG, prompt, n_new, jax.random.key(2), temperature=0.0))

    # Uncached reference loop: full forward each step, argmax.
    seq = np.asarray(prompt)
    for _ in range(n_new):
        logits, _ = transformer.forward(params, jnp.asarray(seq), CFG)
        nxt = int(jnp.argmax(logits[0, -1]))
        seq = np.concatenate([seq, [[nxt]]], axis=1)
    want = seq[:, 8:]
    np.testing.assert_array_equal(got, want)


def test_prefill_cache_matches_full_forward(params):
    """Logits from incremental cached decode == full-sequence forward."""
    tokens = jax.random.randint(jax.random.key(3), (1, 12), 0, CFG.vocab_size)
    full_logits, _ = transformer.forward(params, tokens, CFG)

    cache = transformer.make_kv_cache(CFG, 1, 12, dtype="float32")
    logits_p, cache = transformer.forward(
        params, tokens[:, :4], CFG, kv_cache=cache, cache_index=jnp.int32(0)
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(full_logits[:, :4]), rtol=2e-4, atol=2e-4
    )
    # Decode one token at a time
    for i in range(4, 12):
        step_logits, cache = transformer.forward(
            params, tokens[:, i : i + 1], CFG, kv_cache=cache, cache_index=jnp.int32(i)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]),
            np.asarray(full_logits[:, i]),
            rtol=2e-4,
            atol=2e-4,
        )


def test_generate_respects_context_bound(params):
    prompt = jnp.zeros((1, 60), jnp.int32)
    with pytest.raises(ValueError, match="context_length"):
        generate(params, CFG, prompt, 10, jax.random.key(0))  # 60+10 > 64


def test_batched_generation(params):
    prompt = jax.random.randint(jax.random.key(4), (3, 8), 0, CFG.vocab_size)
    out = generate(params, CFG, prompt, 5, jax.random.key(5))
    assert out.shape == (3, 5)
    assert (np.asarray(out) >= 0).all() and (np.asarray(out) < CFG.vocab_size).all()


def test_sampling_temperature_zero_is_argmax():
    logits = jnp.asarray([[1.0, 3.0, 2.0], [0.5, 0.1, 0.9]])
    out = sample_logits(logits, jax.random.key(0), temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out), [1, 2])


def test_sampling_top_k_restricts_support():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 4.0]])
    draws = set()
    for i in range(50):
        draws.add(int(sample_logits(logits, jax.random.key(i), temperature=1.0, top_k=2)[0]))
    assert draws <= {3, 4}


def test_sampling_min_p_restricts_support():
    """min-p keeps exactly the tokens with prob >= min_p * max prob, and
    the support adapts to confidence (peaked dist -> smaller support)."""
    logits = jnp.asarray([[3.0, 2.9, 0.0, -5.0]])
    ids = [
        int(sample_logits(jnp.asarray(logits), jax.random.key(i),
                          temperature=1.0, min_p=0.5)[0])
        for i in range(64)
    ]
    # p(2.9)/p(3.0) = e^-0.1 ~ 0.90 >= 0.5 kept; p(0)/p(3) ~ 0.05 < 0.5 cut
    assert set(ids) <= {0, 1}
    assert len(set(ids)) == 2  # both survivors actually sampled
    peaked = jnp.asarray([[10.0, 2.9, 0.0, -5.0]])
    ids_p = [
        int(sample_logits(peaked, jax.random.key(i), temperature=1.0,
                          min_p=0.5)[0])
        for i in range(32)
    ]
    assert set(ids_p) == {0}  # confident dist -> support collapses


def test_sampling_top_p_restricts_support():
    # Peaked distribution: token 0 carries ~88% of the mass.
    logits = jnp.asarray([[5.0, 3.0, 0.0, -1.0, -2.0]])
    draws = set()
    for i in range(50):
        draws.add(int(sample_logits(logits, jax.random.key(i), temperature=1.0, top_p=0.5)[0]))
    assert draws == {0}


def test_generate_text_from_checkpoint(tmp_path):
    """Full CLI path: train 2 steps -> checkpoint -> load -> generate text."""
    from pretraining_llm_tpu.training.trainer import Trainer

    # Byte tokenizer (always available offline); vocab covers its 257 ids.
    cfg = get_preset("tiny").with_overrides(
        {
            "model.vocab_size": 512,
            "data.tokenizer_name": "byte",
            "train.train_steps": 2,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 100,
            "train.checkpoint_dir": str(tmp_path / "ck"),
        }
    )
    t = Trainer(cfg, synthetic_data=True, resume=False)
    t.train()

    params, loaded_cfg = load_model_for_inference(str(tmp_path / "ck"))
    assert loaded_cfg.model.vocab_size == 512
    assert loaded_cfg.data.tokenizer_name == "byte"

    from pretraining_llm_tpu.generation.generate import generate_text

    text = generate_text(str(tmp_path / "ck"), "Hello", max_new_tokens=5, seed=0)
    assert text.startswith("Hello")
    assert len(text) > len("Hello")


def test_prompt_bucketing_reuses_compilation(params):
    """Prompts of different lengths within one power-of-two bucket share a
    compiled executable; greedy output is unaffected by the padding."""
    import importlib

    # The package re-exports the `generate` FUNCTION under the submodule's
    # name, so plain `import ... as` resolves to the function; go via importlib.
    gen_mod = importlib.import_module("pretraining_llm_tpu.generation.generate")

    gen_mod._generate_jit.clear_cache()
    for plen in (17, 23, 30):
        prompt = jax.random.randint(jax.random.key(plen), (1, plen), 0, CFG.vocab_size)
        generate(params, CFG, prompt, 4, jax.random.key(0), temperature=0.0)
    assert gen_mod._generate_jit._cache_size() == 1  # one bucket, one compile

    # Correctness under padding: bucketed greedy == uncached reference loop.
    prompt = jax.random.randint(jax.random.key(9), (1, 19), 0, CFG.vocab_size)
    got = np.asarray(generate(params, CFG, prompt, 6, jax.random.key(2), temperature=0.0))
    seq = np.asarray(prompt)
    for _ in range(6):
        logits, _ = transformer.forward(params, jnp.asarray(seq), CFG)
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    np.testing.assert_array_equal(got, seq[:, 19:])


def test_sharded_decode_matches_single_device(params, mesh8):
    """generate(..., mesh=) with TP/FSDP-sharded params == unsharded decode."""
    from pretraining_llm_tpu.generation.generate import shard_params_for_inference

    prompt = jax.random.randint(jax.random.key(5), (2, 12), 0, CFG.vocab_size)
    want = np.asarray(generate(params, CFG, prompt, 5, jax.random.key(7), temperature=0.0))
    sharded = shard_params_for_inference(params, mesh8)
    got = np.asarray(
        generate(sharded, CFG, prompt, 5, jax.random.key(7), temperature=0.0, mesh=mesh8)
    )
    np.testing.assert_array_equal(got, want)


def test_moe_generation_not_bucketed_and_matches_reference():
    """Pad tokens would enter capacitated MoE routing and perturb real
    tokens' outputs — MoE prompts must not be padded (and greedy decode must
    match the uncached reference loop at an awkward prompt length)."""
    cfg = dataclasses.replace(
        CFG, n_experts=4, experts_per_token=2, expert_capacity_factor=1.25
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (1, 17), 0, cfg.vocab_size)
    got = np.asarray(generate(params, cfg, prompt, 6, jax.random.key(2), temperature=0.0))
    seq = np.asarray(prompt)
    for _ in range(6):
        logits, _ = transformer.forward(params, jnp.asarray(seq), cfg)
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    np.testing.assert_array_equal(got, seq[:, 17:])


def test_evaluate_cli(tmp_path):
    """Train briefly, then the standalone eval CLI reports a sane loss and
    is deterministic across invocations."""
    import json
    import subprocess
    import sys

    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckdir = str(tmp_path / "ck")
    # Real-ish token file: biased byte stream (so val loss < ln(256)).
    rng = np.random.default_rng(0)
    tokens = rng.choice(64, size=80_000).astype(np.uint16)
    data = tmp_path / "val.bin"
    tokens.tofile(data)

    env = dict(os.environ, PLLM_PLATFORM="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "train.py"),
         "--preset", "tiny", "--no-resume",
         "--override", "train.train_steps=30", "train.checkpoint_interval=30",
         "train.eval_interval=0", f"train.checkpoint_dir={ckdir}",
         f"data.train_path={data}", f"data.val_path={data}"],
        capture_output=True, text=True, env=env, timeout=600, cwd=repo,
    )
    assert r.returncode == 0, r.stderr[-2000:]

    def run_eval():
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "evaluate.py"),
             "--model_path", ckdir, "--data", str(data), "--iters", "4"],
            capture_output=True, text=True, env=env, timeout=600, cwd=repo,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        return json.loads(r.stdout.strip().splitlines()[-1])

    a, b = run_eval(), run_eval()
    assert a["val_loss"] == b["val_loss"]  # deterministic eval set
    assert 0 < a["val_loss"] < 6.0
    assert abs(a["val_ppl"] - np.exp(a["val_loss"])) < 1e-2 * a["val_ppl"]


def test_flash_prefill_matches_naive_prefill(params):
    """VERDICT r2 #6: with attention_impl='flash' the cached prefill routes
    through the flash kernel over the local block (no (Tq, Tmax) scores) and
    must match the naive masked-einsum prefill and the full forward."""
    cfg_flash = dataclasses.replace(CFG, attention_impl="flash")
    tokens = jax.random.randint(jax.random.key(5), (2, 16), 0, CFG.vocab_size)
    full_logits, _ = transformer.forward(params, tokens, CFG)

    cache = transformer.make_kv_cache(cfg_flash, 2, 24, dtype="float32")
    logits_f, cache_f = transformer.forward(
        params, tokens, cfg_flash, kv_cache=cache, cache_index=jnp.int32(0)
    )
    np.testing.assert_allclose(
        np.asarray(logits_f), np.asarray(full_logits), rtol=2e-4, atol=2e-4
    )
    # The cache written by the flash prefill then drives correct decode.
    nxt = jnp.argmax(logits_f[:, -1], axis=-1)[:, None]
    step_logits, _ = transformer.forward(
        params, nxt, cfg_flash, kv_cache=cache_f, cache_index=jnp.int32(16)
    )
    ext = jnp.concatenate([tokens, nxt], axis=1)
    full_ext, _ = transformer.forward(params, ext, CFG)
    np.testing.assert_allclose(
        np.asarray(step_logits[:, 0]), np.asarray(full_ext[:, -1]),
        rtol=2e-4, atol=2e-4,
    )


def test_generate_flash_equals_naive_greedy(params):
    """End-to-end: greedy generation is implementation-invariant."""
    cfg_flash = dataclasses.replace(CFG, attention_impl="flash")
    prompt = jax.random.randint(jax.random.key(6), (2, 8), 0, CFG.vocab_size)
    got_n = np.asarray(generate(params, CFG, prompt, 8, jax.random.key(7), temperature=0.0))
    got_f = np.asarray(
        generate(params, cfg_flash, prompt, 8, jax.random.key(7), temperature=0.0)
    )
    np.testing.assert_array_equal(got_n, got_f)


def test_generate_decode_unroll_equals_rolled_greedy(params):
    """decode_unroll_layers only changes the compiled loop structure (no
    inner while -> no per-step cache copies); greedy output must be
    bit-identical to the rolled depth scan."""
    # The unroll knob is stacked-only (the unstacked default has no depth
    # scan to unroll — config validation rejects the combination).
    cfg_stacked = dataclasses.replace(CFG, decode_cache_layout="stacked")
    cfg_unroll = dataclasses.replace(cfg_stacked, decode_unroll_layers=True)
    with pytest.raises(ValueError, match="decode_unroll_layers requires"):
        dataclasses.replace(CFG, decode_unroll_layers=True)
    prompt = jax.random.randint(jax.random.key(16), (2, 8), 0, CFG.vocab_size)
    got_r = np.asarray(
        generate(params, cfg_stacked, prompt, 8, jax.random.key(7), temperature=0.0)
    )
    got_u = np.asarray(
        generate(params, cfg_unroll, prompt, 8, jax.random.key(7), temperature=0.0)
    )
    np.testing.assert_array_equal(got_r, got_u)


@pytest.mark.parametrize(
    "pos,impl",
    [("learned", "naive"), ("rope", "naive"), ("rope", "flash")],
)
def test_ragged_batched_generation_matches_per_row(params, pos, impl):
    """Serving-grade ragged batches: rows with different prompt lengths
    decode in ONE lockstep program (right-padded flash-capable prefill,
    per-row cache roll, left-pad lockstep decode) and each row's greedy
    continuation must equal generating that row alone."""
    cfg = dataclasses.replace(CFG, pos_embed=pos, attention_impl=impl)
    p = (
        params
        if (pos, impl) == ("learned", "naive")
        else transformer.init_params(cfg, jax.random.key(0))
    )
    lengths = [3, 8, 5]
    pmax = max(lengths)
    rows = []
    for i, ln in enumerate(lengths):
        row = jax.random.randint(jax.random.key(20 + i), (ln,), 0, cfg.vocab_size)
        rows.append(jnp.pad(row, (0, pmax - ln)))  # right-pad to P
    batch = jnp.stack(rows)
    n_new = 6

    got = np.asarray(
        generate(
            p, cfg, batch, n_new, jax.random.key(9), temperature=0.0,
            prompt_lengths=jnp.asarray(lengths),
        )
    )
    for i, ln in enumerate(lengths):
        want = np.asarray(
            generate(
                p, cfg, batch[i, :ln][None], n_new, jax.random.key(9),
                temperature=0.0,
            )
        )
        np.testing.assert_array_equal(got[i], want[0], err_msg=f"row {i} (len {ln})")


def test_ragged_generation_validation(params):
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate(
            params, CFG, jnp.zeros((2, 4), jnp.int32), 4, jax.random.key(0),
            prompt_lengths=jnp.asarray([2, 3, 4]),  # wrong batch size
        )
    with pytest.raises(ValueError, match="prompt_lengths"):
        generate(
            params, CFG, jnp.zeros((2, 4), jnp.int32), 4, jax.random.key(0),
            prompt_lengths=jnp.asarray([2, 9]),  # exceeds P
        )


def test_generate_text_batch_ragged_cli(tmp_path):
    """Batched ragged text generation from a checkpoint: one compiled
    program for prompts of different lengths; each output extends its own
    prompt and matches the single-prompt path under greedy decoding."""
    from pretraining_llm_tpu.generation.generate import (
        generate_text,
        generate_text_batch,
    )
    from pretraining_llm_tpu.training.trainer import Trainer

    cfg = get_preset("tiny").with_overrides(
        {
            "model.vocab_size": 512,
            "data.tokenizer_name": "byte",
            "train.train_steps": 2,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 100,
            "train.checkpoint_dir": str(tmp_path / "ck"),
        }
    )
    Trainer(cfg, synthetic_data=True, resume=False).train()

    prompts = ["Hello", "ab", "The quick brown"]
    outs = generate_text_batch(
        str(tmp_path / "ck"), prompts, max_new_tokens=5, temperature=0.0
    )
    assert len(outs) == 3
    for prompt, out in zip(prompts, outs):
        assert out.startswith(prompt)
        # (No length assertion: a 2-step byte model can argmax ids outside
        # the byte-decodable range, which decode to "".) The real check:
        # the ragged batch row equals the single-prompt path exactly.
        single = generate_text(
            str(tmp_path / "ck"), prompt, max_new_tokens=5, temperature=0.0
        )
        assert out == single, (out, single)


def test_stop_token_freezes_finished_rows(params):
    """Once a row samples the stop token it emits only the stop token for
    the remaining steps; tokens before the stop match the un-stopped run."""
    prompt = jax.random.randint(jax.random.key(30), (2, 6), 0, CFG.vocab_size)
    base = np.asarray(
        generate(params, CFG, prompt, 10, jax.random.key(3), temperature=0.0)
    )
    stop = int(base[0, 2])  # a token the greedy run actually emits
    got = np.asarray(
        generate(
            params, CFG, prompt, 10, jax.random.key(3), temperature=0.0,
            stop_token=stop,
        )
    )
    for row in range(2):
        hits = np.where(base[row] == stop)[0]
        if hits.size == 0:
            np.testing.assert_array_equal(got[row], base[row])
            continue
        first = int(hits[0])
        np.testing.assert_array_equal(got[row, : first + 1], base[row, : first + 1])
        assert (got[row, first:] == stop).all(), got[row]


def test_generate_text_works_for_moe_checkpoint(tmp_path):
    """generate_text must keep working for MoE checkpoints: single-prompt
    (uniform-length) batches bypass the ragged machinery MoE rejects."""
    from pretraining_llm_tpu.generation.generate import (
        generate_text,
        generate_text_batch,
    )
    from pretraining_llm_tpu.training.trainer import Trainer

    cfg = get_preset("tiny").with_overrides(
        {
            "model.vocab_size": 512,
            "model.n_experts": 2,
            "model.experts_per_token": 1,
            "model.expert_capacity_factor": 4.0,
            "data.tokenizer_name": "byte",
            "train.train_steps": 2,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 100,
            "train.checkpoint_dir": str(tmp_path / "ck"),
        }
    )
    Trainer(cfg, synthetic_data=True, resume=False).train()
    text = generate_text(str(tmp_path / "ck"), "Hello", max_new_tokens=4, temperature=0.0)
    assert text.startswith("Hello")
    # Ragged (different-length) MoE batches are rejected with a clear error.
    with pytest.raises(ValueError, match="equal-length"):
        generate_text_batch(
            str(tmp_path / "ck"), ["Hello", "ab"], max_new_tokens=4
        )


@pytest.mark.parametrize("gqa", [False, True])
@pytest.mark.parametrize("cache_kind", ["compute", "int8"])
def test_chunked_prefill_blockwise_matches_full_forward(gqa, cache_kind):
    """Chunked prefill at a nonzero offset routes through rectangular
    blockwise attention (O(block) memory, no (Tq, Tmax) scores, grouped
    cache never expanded) and must track the full-sequence forward — MHA
    and GQA, exact and int8-quantized caches."""
    cfg = dataclasses.replace(
        CFG, attention_impl="flash", n_kv_heads=2 if gqa else None,
        pos_embed="rope", kv_cache_dtype=cache_kind,
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(7), (2, 24), 0, cfg.vocab_size)
    full, _ = transformer.forward(params, tokens, cfg)

    cache = transformer.make_kv_cache(
        cfg, 2, 24, dtype=None if cache_kind == "int8" else "float32"
    )
    got = []
    for start in (0, 8, 16):  # chunk 0 takes the flash shortcut, rest blockwise
        logits, cache = transformer.forward(
            params, tokens[:, start : start + 8], cfg, kv_cache=cache,
            cache_index=jnp.int32(start),
        )
        got.append(logits)
    got = jnp.concatenate(got, axis=1)
    if cache_kind == "int8":
        err = float(jnp.abs(got - full).max())
        assert err < 0.05 * float(jnp.abs(full).max()), err
    else:
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-4
        )


def test_chunked_prefill_with_traced_offset_matches_full_forward():
    """The TRACED-offset sub-path (cache_index as a jit argument: no
    frontier slice, offset flows into the causal mask inside the scan)
    must match the full forward too."""
    cfg = dataclasses.replace(CFG, attention_impl="flash", pos_embed="rope")
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(8), (2, 24), 0, cfg.vocab_size)
    full, _ = transformer.forward(params, tokens, cfg)

    @jax.jit
    def chunk(params, toks, cache, idx):
        return transformer.forward(
            params, toks, cfg, kv_cache=cache, cache_index=idx
        )

    cache = transformer.make_kv_cache(cfg, 2, 24, dtype="float32")
    got = []
    for start in (0, 8, 16):
        logits, cache = chunk(
            params, tokens[:, start : start + 8], cache, jnp.int32(start)
        )
        got.append(logits)
    got = jnp.concatenate(got, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize(
    "variant", ["plain", "biased_head", "moe"]
)
def test_cast_params_for_inference_bit_identical(variant):
    """Pre-casting matmul weights to compute dtype is bit-identical (the
    forward casts at every use site anyway) and leaves the fp32-consumed
    leaves alone: norm params, the lm_head bias, the MoE router."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp
    from jax.tree_util import tree_flatten_with_path

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.generation.generate import (
        cast_params_for_inference, generate,
    )
    from pretraining_llm_tpu.models import transformer

    cfg = get_preset("tiny").model
    if variant == "biased_head":
        cfg = dc.replace(cfg, tie_embeddings=False, lm_head_bias=True)
    elif variant == "moe":
        cfg = dc.replace(cfg, n_experts=4, experts_per_token=2)
    p = transformer.init_params(cfg, jax.random.key(0))
    # Zero-initialized leaves (lm_head bias, norm biases) would make the
    # forward comparison vacuous (0.0 rounds exactly to bf16): randomize
    # EVERY float leaf so a wrongly-cast leaf actually changes the logits.
    leaves, treedef = jax.tree_util.tree_flatten(p)
    keys = jax.random.split(jax.random.key(99), len(leaves))
    p = jax.tree_util.tree_unflatten(treedef, [
        (jax.random.normal(k, l.shape, jnp.float32) * 0.05).astype(l.dtype)
        if jnp.issubdtype(l.dtype, jnp.floating) else l
        for k, l in zip(keys, leaves)
    ])
    pc = cast_params_for_inference(p, cfg)
    cdt = jnp.dtype(cfg.compute_dtype)
    # Hand-listed fp32-consumed leaf names (independent of the
    # implementation's path predicate).
    fp32_expected = {"ln1/scale", "ln1/bias", "ln2/scale", "ln2/bias",
                     "final_norm/scale", "final_norm/bias", "lm_head/bias"}
    fp32_suffixes = tuple(fp32_expected) + ("router",)
    for path, leaf in tree_flatten_with_path(pc)[0]:
        name = "/".join(str(getattr(k, "key", "")) for k in path)
        if name.endswith(fp32_suffixes):
            assert leaf.dtype == jnp.float32, name
        elif jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == cdt, name

    x = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    l1, l2 = transformer.forward(p, x, cfg), transformer.forward(pc, x, cfg)
    if isinstance(l1, tuple):
        l1, l2 = l1[0], l2[0]
    assert bool(jnp.all(l1 == l2))
    if variant != "moe":  # ragged-free dense decode path
        g1 = generate(p, cfg, x, 8, jax.random.key(2), temperature=0.0)
        g2 = generate(pc, cfg, x, 8, jax.random.key(2), temperature=0.0)
        assert bool(jnp.all(g1 == g2))


def _sample_logits_fullsort_reference(
    logits, key, *, temperature=1.0, top_k=None, top_p=None, min_p=None
):
    """The pre-top_k-rework sampler (full jnp.sort for the k-th threshold
    and a second sort for top-p), inlined as the distribution-identity
    reference: filters are value-threshold masks, so the lax.top_k
    rework must pick the SAME token for the same key, ties included."""
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    bad = jnp.any(jnp.isnan(logits) | (logits == jnp.inf), axis=-1)
    logits = logits / temperature
    if min_p is not None and 0.0 < min_p <= 1.0:
        cutoff = jnp.max(logits, axis=-1, keepdims=True) + jnp.log(min_p)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff_logit = jnp.take_along_axis(
            sorted_desc, cutoff_idx[:, None], axis=-1
        )
        logits = jnp.where(logits < cutoff_logit, -jnp.inf, logits)
    sampled = jax.random.categorical(key, logits, axis=-1)
    return jnp.where(bad, jnp.int32(-1), sampled.astype(jnp.int32))


@pytest.mark.parametrize("knobs", [
    dict(top_k=4),
    dict(top_k=1),
    dict(top_p=0.7),
    dict(top_k=4, top_p=0.7),
    dict(top_k=3, top_p=0.95, min_p=0.01),
    dict(top_k=50),  # k >= V: no-op filter
])
def test_sample_logits_topk_rework_distribution_identity(knobs):
    """The lax.top_k sampler must be token-for-token identical to the
    old full-sort implementation — same masked distribution, same
    categorical draw per key — including logits with exact ties AT the
    k-th value and at the nucleus cutoff."""
    rng = np.random.default_rng(42)
    for trial in range(6):
        logits = rng.normal(size=(5, 16)).astype(np.float32) * 3.0
        if trial % 2:
            # Inject ties straddling the thresholds: rows where several
            # entries share the k-th-largest value exactly.
            logits[0, :6] = 1.25
            logits[1, 3:9] = logits[1, 3]
            logits[2] = 0.0
        jl = jnp.asarray(logits)
        for seed in range(3):
            key = jax.random.key(trial * 10 + seed)
            got = sample_logits(jl, key, temperature=0.8, **knobs)
            want = _sample_logits_fullsort_reference(
                jl, key, temperature=0.8, **knobs
            )
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
