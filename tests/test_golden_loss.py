"""Golden-loss pretraining on REAL text (SURVEY §4: CPU-runnable golden test).

The reference's whole purpose is next-token pretraining on natural language
(`/root/reference/scripts/train_transformer.py:139-140`); a synthetic stream
can't prove the end-to-end pipeline learns real structure. This harvests
genuine English prose from the machine (the same source the parity experiment
uses), runs the real pipeline — corpus -> byte tokenize -> uint16 memmap ->
seeded loader -> compiled train step — and pins the loss against bounds a
byte-level model must hit on English text.
"""

import os

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.data import loader
from pretraining_llm_tpu.training import train_step as ts


def _prose_roots():
    """Candidate doc-harvest roots, derived from THIS interpreter's layout
    (not a hardcoded venv path — ADVICE r2)."""
    import site
    import sysconfig

    roots = []
    try:
        roots.extend(site.getsitepackages())
    except Exception:
        pass
    purelib = sysconfig.get_paths().get("purelib")
    if purelib:
        roots.append(purelib)
    return [r for i, r in enumerate(roots) if r not in roots[:i] and os.path.isdir(r)]


@pytest.fixture(scope="module")
def real_text_bin(tmp_path_factory):
    """~300 KB of real prose -> byte-tokenized uint16 memmap."""
    chunks, total = [], 0
    for root in _prose_roots():
        for dirpath, _, names in sorted(os.walk(root)):
            for name in sorted(names):
                if not name.endswith((".rst", ".md")):
                    continue
                p = os.path.join(dirpath, name)
                try:
                    data = open(p, "rb").read()
                except OSError:
                    continue
                if b"\x00" in data or len(data) < 2000:
                    continue
                chunks.append(data)
                total += len(data)
                if total > 300_000:
                    break
            if total > 300_000:
                break
        if total > 300_000:
            break
    if total <= 100_000:
        pytest.skip("no harvestable prose in site-packages on this machine")
    path = tmp_path_factory.mktemp("golden") / "train.bin"
    tokens = np.frombuffer(b"\n\n".join(chunks), np.uint8).astype(np.uint16)
    tokens.tofile(path)
    return str(path)


LLAMA_OVERRIDES = {
    # BASELINE config #4's architecture at toy scale: RoPE + SwiGLU +
    # RMSNorm + GQA + untied head, and BIASLESS projections like the real
    # llama presets (config.py `_llama_model`).
    "model.pos_embed": "rope",
    "model.activation": "swiglu",
    "model.norm": "rmsnorm",
    "model.n_kv_heads": 2,
    "model.tie_embeddings": False,
    "model.qkv_bias": False,
    "model.mlp_bias": False,
}


@pytest.mark.parametrize(
    "overrides,seed,check_sampling",
    [({}, 7, True), (LLAMA_OVERRIDES, 11, False)],
    ids=["gpt2-flavor", "llama-flavor"],
)
def test_pretrain_on_real_text_reaches_golden_loss(
    real_text_bin, overrides, seed, check_sampling
):
    """300 steps of a tiny byte-level model on real English prose, for the
    GPT-2-flavored tiny preset AND the Llama-style layer stack.

    Bounds: byte-level entropy of English is ~1.0-2.2 bits/byte for strong
    models; a 0.05M-param model at step 300 won't get near that, but it MUST
    beat the unigram byte entropy of ASCII prose (~3.0 nats) from the
    ln(256)=5.55 start. Failing either bound means the pipeline is broken
    (data mangled, shift-by-one wrong, lr dead), not that the model is small.
    """
    import jax.numpy as jnp

    cfg = get_preset("tiny").with_overrides(
        {
            "train.train_steps": 300,
            "train.lr": 3e-3,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            **overrides,
        }
    )
    it = loader.get_batch_iterator(
        real_text_bin, cfg.train.batch_size, cfg.model.context_length, seed=seed
    )
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, mesh=None)
    first = None
    for _ in range(cfg.train.train_steps):
        x, y = next(it)
        state, metrics = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert 5.0 < first < 6.0, first  # ~ln(256) at init
    assert last < 3.0, (first, last)  # beat the unigram byte entropy

    if not check_sampling:
        return
    # The learned distribution is textual: sampled bytes are printable ASCII.
    from pretraining_llm_tpu.generation.generate import generate

    prompt = jnp.asarray(np.frombuffer(b"the ", np.uint8).astype(np.int32))[None]
    out = generate(
        state["params"], cfg.model, prompt, 32, jax.random.key(3), temperature=0.8
    )
    sampled = bytes(int(t) for t in np.asarray(out)[0])
    printable = sum(1 for b in sampled if 9 <= b < 127)
    assert printable >= len(sampled) * 0.9, sampled
