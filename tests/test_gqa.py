"""Grouped-query attention: param shapes, MHA equivalence, cached decode.

Beyond-parity feature (the reference is MHA-only with per-head Linears,
attention.py:29-31). The decisive numeric check: a GQA model whose KV heads
are replicated into a full MHA weight tensor must produce identical logits.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.models import transformer


def _cfg(**kw):
    base = dict(
        vocab_size=97,
        context_length=32,
        d_model=32,
        n_heads=4,
        n_kv_heads=2,
        n_layers=2,
        pos_embed="rope",
        param_dtype="float32",
        compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


def test_gqa_validation():
    import pytest

    with pytest.raises(ValueError):
        ModelConfig(n_heads=4, n_kv_heads=3)
    with pytest.raises(ValueError):
        ModelConfig(n_heads=4, n_kv_heads=8)
    ModelConfig(n_heads=4, n_kv_heads=1)  # MQA is valid


def test_gqa_param_count_matches_analytic():
    for g in (1, 2):
        cfg = _cfg(n_kv_heads=g)
        params = transformer.init_params(cfg, jax.random.key(0))
        actual = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
        assert actual == cfg.num_params(), (g, actual, cfg.num_params())
    # GQA must be smaller than MHA
    assert _cfg(n_kv_heads=2).num_params() < _cfg(n_kv_heads=None).num_params()


def test_gqa_equals_mha_with_replicated_kv():
    cfg = _cfg(n_kv_heads=2, qkv_bias=True)
    mha = dataclasses.replace(cfg, n_kv_heads=None)
    params = transformer.init_params(cfg, jax.random.key(0))

    # Build MHA params: replicate each KV head group-size times into wqkv.
    n_rep = cfg.n_heads // cfg.kv_heads
    blocks = dict(params["blocks"])
    attn = blocks["attn"]
    wq = attn["wq"]  # (L, D, H, Dh)
    wkv = attn["wkv"]  # (L, D, 2, G, Dh)
    wk = jnp.repeat(wkv[:, :, 0], n_rep, axis=2)  # (L, D, H, Dh)
    wv = jnp.repeat(wkv[:, :, 1], n_rep, axis=2)
    wqkv = jnp.stack([wq, wk, wv], axis=2)  # (L, D, 3, H, Dh)
    bq = attn["bq"]  # (L, H, Dh)
    bkv = attn["bkv"]  # (L, 2, G, Dh)
    bqkv = jnp.stack(
        [bq, jnp.repeat(bkv[:, 0], n_rep, axis=1), jnp.repeat(bkv[:, 1], n_rep, axis=1)],
        axis=1,
    )
    keep = {k: v for k, v in attn.items() if k in ("wo", "bo")}
    blocks["attn"] = {**keep, "wqkv": wqkv, "bqkv": bqkv}
    mha_params = {**params, "blocks": blocks}

    tokens = jax.random.randint(jax.random.key(1), (2, cfg.context_length), 0, cfg.vocab_size)
    logits_gqa, _ = transformer.forward(params, tokens, cfg)
    logits_mha, _ = transformer.forward(mha_params, tokens, mha)
    np.testing.assert_allclose(
        np.asarray(logits_gqa), np.asarray(logits_mha), rtol=1e-5, atol=1e-5
    )


def test_gqa_cache_shape_and_decode_matches_full_forward():
    cfg = _cfg(n_kv_heads=1)  # MQA: maximal cache shrink
    params = transformer.init_params(cfg, jax.random.key(0))
    b, t = 2, 8
    tokens = jax.random.randint(jax.random.key(1), (b, t), 0, cfg.vocab_size)

    cache = transformer.make_kv_cache(cfg, b, cfg.context_length)
    # Default container is the unstacked per-layer tuple; MQA caches ONE
    # kv head per layer either way (the GQA memory win under test).
    assert cache["layers"][0]["k"].shape == (
        b, cfg.context_length, 1, cfg.head_dim
    )
    stacked = transformer.make_kv_cache(
        dataclasses.replace(cfg, decode_cache_layout="stacked"),
        b, cfg.context_length,
    )
    assert stacked["k"].shape == (
        cfg.n_layers, b, cfg.context_length, 1, cfg.head_dim
    )

    full_logits, _ = transformer.forward(params, tokens, cfg)

    # Incremental decode: feed one token at a time through the cache.
    step_logits = []
    idx = jnp.zeros((), jnp.int32)
    for i in range(t):
        logits, cache = transformer.forward(
            params, tokens[:, i : i + 1], cfg, kv_cache=cache, cache_index=idx
        )
        step_logits.append(logits[:, 0])
        idx = idx + 1
    stacked = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(
        np.asarray(stacked), np.asarray(full_logits), rtol=2e-4, atol=2e-4
    )


def test_gqa_grads_flow():
    cfg = _cfg()
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.context_length), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    grads = jax.grad(transformer.loss_fn)(params, tokens, targets, cfg)
    attn = grads["blocks"]["attn"]
    assert float(jnp.abs(attn["wq"]).max()) > 0
    assert float(jnp.abs(attn["wkv"]).max()) > 0


def test_gqa_wkv_tp_sharding_decision():
    """wkv shards its G head dim over 'tensor' iff G divides the axis.

    VERDICT r2 #10: G % tp == 0 -> shard (each TP rank computes only its KV
    heads); otherwise replicate and pay the documented gradient all-reduce.
    """
    from pretraining_llm_tpu.parallel.sharding import param_pspec_tree

    cfg = _cfg(n_kv_heads=2, qkv_bias=True)  # wkv (D, 2, 2, Dh)
    params = transformer.init_params(cfg, jax.random.key(0))

    # tp=2 divides G=2: head dim sharded for wkv AND its bias.
    specs = param_pspec_tree(params, tensor_size=2)
    assert tuple(specs["blocks"]["attn"]["wkv"]) == (None, "fsdp", None, "tensor", None)
    assert tuple(specs["blocks"]["attn"]["bkv"]) == (None, None, "tensor", None)

    # tp=4 does not divide G=2: replicated G (the deliberate fallback).
    specs = param_pspec_tree(params, tensor_size=4)
    assert tuple(specs["blocks"]["attn"]["wkv"]) == (None, "fsdp", None, None, None)
    assert tuple(specs["blocks"]["attn"]["bkv"]) == (None, None, None, None)

    # No tensor axis (default): replicated G, same as before.
    specs = param_pspec_tree(params)
    assert tuple(specs["blocks"]["attn"]["wkv"]) == (None, "fsdp", None, None, None)
