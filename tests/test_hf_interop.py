"""Hugging Face GPT-2 interop: import -> logit parity -> export round-trip.

The importer maps GPT2LMHeadModel weights onto the stacked functional
pytree; the proof is end-to-end logit agreement between the HF torch
forward and this framework's forward on the same tokens, plus an exact
weight round-trip back out.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))

from import_hf_checkpoint import import_hf_model  # noqa: E402
from export_hf_checkpoint import export_params_to_hf  # noqa: E402

from pretraining_llm_tpu.models import transformer  # noqa: E402


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=32, n_embd=48, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    return model


def _import(hf_model):
    return import_hf_model(hf_model)


def test_import_config_and_shapes(hf_model):
    cfg, params = _import(hf_model)
    assert cfg.vocab_size == 97
    assert cfg.context_length == 32
    assert cfg.n_layers == 2
    assert cfg.n_heads == 4
    assert cfg.tie_embeddings and cfg.qkv_bias and cfg.use_output_proj
    assert params["blocks"]["attn"]["wqkv"].shape == (2, 48, 3, 4, 12)
    assert "lm_head" not in params  # tied


def test_imported_logits_match_hf(hf_model):
    """The entire point: framework forward == HF forward on the imported
    weights (fp32, highest-precision matmuls)."""
    import dataclasses

    cfg, params = _import(hf_model)
    cfg = dataclasses.replace(cfg, compute_dtype="float32")
    toks = np.random.default_rng(1).integers(0, 97, (2, 20))
    with torch.no_grad():
        want = hf_model(torch.from_numpy(toks)).logits.numpy()
    with jax.default_matmul_precision("highest"):
        got, _ = transformer.forward(
            params, jnp.asarray(toks), cfg
        )
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_export_round_trip_exact(hf_model):
    """import -> export reproduces every HF weight bit-exactly."""
    cfg, params = _import(hf_model)
    back = export_params_to_hf(params, cfg)
    orig = hf_model.state_dict()
    out = back.state_dict()
    for k, v in orig.items():
        if k.endswith((".attn.bias", ".attn.masked_bias")):
            continue  # mask buffers, not weights
        np.testing.assert_array_equal(
            v.numpy(), out[k].numpy(), err_msg=k
        )


def test_import_rejects_unmapped_keys(hf_model):
    from import_hf_checkpoint import import_hf_state_dict

    sd = {k: v.detach().numpy() for k, v in hf_model.state_dict().items()}
    sd["transformer.h.0.adapter.weight"] = np.zeros((4, 4), np.float32)
    with pytest.raises(ValueError, match="does not map"):
        import_hf_state_dict(sd, 4)


def test_import_rejects_divergent_numerics(hf_model):
    """State-dict shapes cannot catch an exact-erf gelu or attn-scale
    variant; the config gate must."""
    import copy

    m = copy.deepcopy(hf_model)
    m.config.activation_function = "gelu"  # exact erf, not gelu_new
    with pytest.raises(ValueError, match="numerics"):
        import_hf_model(m)
    m2 = copy.deepcopy(hf_model)
    m2.config.scale_attn_by_inverse_layer_idx = True
    with pytest.raises(ValueError, match="numerics"):
        import_hf_model(m2)


def test_import_mlp_ratio_reconstructs_awkward_d_ff():
    """int(mlp_ratio * d_model) must equal n_inner even for pairs where
    the bare ratio truncates low (e.g. 220/49)."""
    cfg = transformers.GPT2Config(
        vocab_size=31, n_positions=8, n_embd=49, n_layer=1, n_head=7,
        n_inner=220, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(1)
    m = transformers.GPT2LMHeadModel(cfg).eval()
    icfg, params = import_hf_model(m)
    assert icfg.d_ff == 220
    assert params["blocks"]["mlp"]["w1"].shape == (1, 49, 220)


def test_export_rejects_windowed_model(hf_model):
    import dataclasses

    cfg, params = _import(hf_model)
    with pytest.raises(ValueError, match="failing properties"):
        export_params_to_hf(params, dataclasses.replace(cfg, sliding_window=8))


def test_export_rejects_non_gpt2_architecture(hf_model):
    import dataclasses

    cfg, params = _import(hf_model)
    with pytest.raises(ValueError, match="failing properties"):
        export_params_to_hf(params, dataclasses.replace(cfg, activation="swiglu"))


def test_imported_checkpoint_generates(tmp_path, hf_model):
    """Full CLI contract: save as a framework checkpoint, load through the
    generation loader, greedy-decode a few tokens."""
    import dataclasses

    from pretraining_llm_tpu.config import Config, DataConfig
    from pretraining_llm_tpu.generation.generate import (
        generate, load_model_for_inference,
    )
    from pretraining_llm_tpu.training import checkpoint as ckpt

    cfg, params = _import(hf_model)
    full = Config(model=cfg, data=DataConfig(tokenizer_name="gpt2"),
                  name="imported-hf-gpt2")
    params = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
    ckpt.save_checkpoint(
        str(tmp_path / "ck"), 0, {"params": params},
        extra={"step": 0, "config": dataclasses.asdict(full), "preset": full.name},
    )
    loaded, loaded_cfg = load_model_for_inference(str(tmp_path / "ck"))
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    toks = generate(loaded, loaded_cfg.model, prompt, 6, jax.random.key(0),
                    temperature=0.0)
    assert toks.shape == (1, 6)
    # Greedy continuation agrees with the HF model's own greedy decode.
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor([[1, 2, 3, 4]]), max_new_tokens=6, do_sample=False,
            pad_token_id=0,
        )
    np.testing.assert_array_equal(np.asarray(toks)[0], hf_out[0, 4:].numpy())
