"""Reference-checkpoint import: torch .pt -> framework checkpoint -> decode.

Builds a synthetic checkpoint in the reference's exact state-dict layout
(torch.save({'model_state_dict': ...}), per-head K/Q/V Linears, no W_O,
ReLU MLP, untied biased lm_head — reference scripts/train_transformer.py:104
+ src/models/*), imports it, and checks the imported model's logits against
an independent numpy forward of the reference semantics (written from the
SURVEY §2.5 spec, not the reference code).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from pretraining_llm_tpu.models import transformer
from scripts.import_torch_checkpoint import _strip_prefixes, import_state_dict

V, T, D, H, L = 89, 16, 24, 3, 2
DH = D // H


def _make_reference_state_dict(seed=0):
    g = torch.Generator().manual_seed(seed)
    sd = {}

    def t(*shape):
        return torch.randn(*shape, generator=g) * 0.2

    sd["token_embed.weight"] = t(V, D)
    sd["position_embed.weight"] = t(T, D)
    for i in range(L):
        sd[f"attn_blocks.{i}.ln1.weight"] = 1 + 0.1 * t(D)
        sd[f"attn_blocks.{i}.ln1.bias"] = 0.1 * t(D)
        for h in range(H):
            for name in ("query", "key", "value"):
                sd[f"attn_blocks.{i}.attn.heads.{h}.{name}.weight"] = t(DH, D)
            # per-head mask buffer the importer must drop (reference B10)
            sd[f"attn_blocks.{i}.attn.heads.{h}.tril"] = torch.tril(
                torch.ones(T, T)
            )
        sd[f"attn_blocks.{i}.ln2.weight"] = 1 + 0.1 * t(D)
        sd[f"attn_blocks.{i}.ln2.bias"] = 0.1 * t(D)
        sd[f"attn_blocks.{i}.mlp.hidden.weight"] = t(4 * D, D)
        sd[f"attn_blocks.{i}.mlp.hidden.bias"] = 0.1 * t(4 * D)
        sd[f"attn_blocks.{i}.mlp.proj.weight"] = t(D, 4 * D)
        sd[f"attn_blocks.{i}.mlp.proj.bias"] = 0.1 * t(D)
    sd["layer_norm.weight"] = 1 + 0.1 * t(D)
    sd["layer_norm.bias"] = 0.1 * t(D)
    sd["lm_head.weight"] = t(V, D)
    sd["lm_head.bias"] = 0.1 * t(V)
    sd["pos_idxs"] = torch.arange(T)
    return sd


def _reference_forward_numpy(sd, tokens):
    """Independent numpy forward of the SURVEY §2.5 semantics."""

    def ln(x, w, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * w + b

    np_sd = {k: v.numpy().astype(np.float64) for k, v in sd.items() if v.dtype.is_floating_point}
    x = np_sd["token_embed.weight"][tokens] + np_sd["position_embed.weight"][: tokens.shape[1]]
    mask = np.tril(np.ones((tokens.shape[1], tokens.shape[1]), bool))
    for i in range(L):
        hld = ln(x, np_sd[f"attn_blocks.{i}.ln1.weight"], np_sd[f"attn_blocks.{i}.ln1.bias"])
        heads = []
        for h in range(H):
            q = hld @ np_sd[f"attn_blocks.{i}.attn.heads.{h}.query.weight"].T
            k = hld @ np_sd[f"attn_blocks.{i}.attn.heads.{h}.key.weight"].T
            v = hld @ np_sd[f"attn_blocks.{i}.attn.heads.{h}.value.weight"].T
            s = q @ k.transpose(0, 2, 1) / np.sqrt(DH)
            s = np.where(mask, s, -np.inf)
            p = np.exp(s - s.max(-1, keepdims=True))
            p = p / p.sum(-1, keepdims=True)
            heads.append(p @ v)
        x = x + np.concatenate(heads, -1)
        hld = ln(x, np_sd[f"attn_blocks.{i}.ln2.weight"], np_sd[f"attn_blocks.{i}.ln2.bias"])
        hid = np.maximum(
            hld @ np_sd[f"attn_blocks.{i}.mlp.hidden.weight"].T
            + np_sd[f"attn_blocks.{i}.mlp.hidden.bias"],
            0.0,
        )
        x = x + hid @ np_sd[f"attn_blocks.{i}.mlp.proj.weight"].T + np_sd[
            f"attn_blocks.{i}.mlp.proj.bias"
        ]
    x = ln(x, np_sd["layer_norm.weight"], np_sd["layer_norm.bias"])
    return x @ np_sd["lm_head.weight"].T + np_sd["lm_head.bias"]


def test_import_matches_reference_semantics(tmp_path):
    sd = _make_reference_state_dict()
    pt = tmp_path / "reference.pt"
    # Reference schema incl. DDP/compile prefixes the importer must strip.
    torch.save(
        {"model_state_dict": {f"module._orig_mod.{k}": v for k, v in sd.items()}},
        pt,
    )

    raw = torch.load(pt, map_location="cpu", weights_only=True)
    clean = _strip_prefixes({k: v.numpy() for k, v in raw["model_state_dict"].items()})
    clean = {k: v for k, v in clean.items() if not k.endswith((".tril", "pos_idxs"))}
    cfg, params = import_state_dict(clean)

    assert cfg.vocab_size == V and cfg.n_layers == L and cfg.n_heads == H
    assert not cfg.use_output_proj and not cfg.tie_embeddings and cfg.lm_head_bias

    tokens = np.arange(2 * T).reshape(2, T) % V
    want = _reference_forward_numpy(sd, tokens)

    import dataclasses

    fcfg = dataclasses.replace(cfg, compute_dtype="float32")
    params_j = jax.tree.map(jnp.asarray, params)
    got, _ = transformer.forward(params_j, jnp.asarray(tokens), fcfg)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_import_cli_roundtrip_generates(tmp_path):
    """Full CLI path: torch.save -> import script -> generate_text loads it."""
    import os
    import subprocess
    import sys

    sd = _make_reference_state_dict(seed=1)
    pt = tmp_path / "ref.pt"
    torch.save({"model_state_dict": sd}, pt)
    out_dir = tmp_path / "imported"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PLLM_PLATFORM="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(repo_root, "scripts", "import_torch_checkpoint.py"),
         str(pt), "--out_dir", str(out_dir), "--tokenizer", "byte"],
        capture_output=True, text=True, env=env, timeout=300, cwd=repo_root,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "imported" in r.stdout

    from pretraining_llm_tpu.generation.generate import generate_text

    text = generate_text(str(out_dir), "ab", max_new_tokens=4, seed=0)
    assert text.startswith("ab") and len(text) > 2


def test_import_rejects_unmapped_weights():
    """Extra trained weights (a deviated architecture) fail loudly."""
    sd = {
        k: v.numpy()
        for k, v in _make_reference_state_dict().items()
        if v.dtype.is_floating_point and not k.endswith(".tril")
    }
    sd["attn_blocks.0.attn.proj.weight"] = np.zeros((D, D), np.float32)
    with pytest.raises(ValueError, match="does not map"):
        import_state_dict(sd)


def test_strip_prefixes_handles_compile_of_ddp():
    sd = {"_orig_mod.module.token_embed.weight": 1, "module.x": 2, "y": 3}
    assert set(_strip_prefixes(sd)) == {"token_embed.weight", "x", "y"}


def test_export_import_roundtrip_identity():
    """export_params is the exact inverse of import_state_dict."""
    from scripts.export_torch_checkpoint import export_params

    sd = {
        k: v.numpy()
        for k, v in _make_reference_state_dict(seed=2).items()
        if v.dtype.is_floating_point and not k.endswith(".tril")
    }
    cfg, params = import_state_dict(sd)
    back = export_params(cfg, params)
    for k in sd:
        np.testing.assert_array_equal(back[k], sd[k], err_msg=k)
    # Export also synthesizes the reference's registered buffers so its
    # strict load_state_dict finds every key.
    extra = set(back) - set(sd)
    assert extra == {"pos_idxs"} | {
        f"attn_blocks.{i}.attn.heads.{h}.tril" for i in range(L) for h in range(H)
    }


def test_export_rejects_non_reference_shapes():
    from scripts.export_torch_checkpoint import export_params

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.models import transformer as tf

    cfg = get_preset("tiny").model  # standard GPT-2 shape: W_O + tied head
    params = tf.init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="reference-shaped"):
        export_params(cfg, params)
