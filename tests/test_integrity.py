"""Output-integrity sentinel: golden probes, KV/weight checksums, and
divergent-replica quarantine.

The correctness bar: silent-wrong state (a flipped shared KV page, an
in-place weight mutation, an out-of-vocab token from a corrupted
sampling path) must be DETECTED and contained — the divergent replica
quarantined, its in-flight work redriven bit-identically, a corrupted
cache page re-prefilled privately — while with every knob off the
detectors cost nothing on the decode hot path (no new device pulls,
spy-enforced).

Unit layer: probe/digest/fingerprint primitives. Integration layer:
verify-on-acquire identity runs, checkpoint checksum fallback, and
fleet drills where the ONLY signal is wrong output.
"""

import dataclasses
import glob
import importlib.util
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.paged import BlockAllocator
from pretraining_llm_tpu.generation.prefix_cache import PrefixCache
from pretraining_llm_tpu.generation.sampling import sample_logits
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.capacity import DECISION_KINDS
from pretraining_llm_tpu.observability.events import EVENT_KINDS, EventBus
from pretraining_llm_tpu.resilience import integrity
from pretraining_llm_tpu.resilience.faults import (
    ServingFault,
    ServingFaultInjector,
    parse_serving_faults,
)
from pretraining_llm_tpu.training import checkpoint as ckpt

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
BS = 8  # block_size used throughout

# The offline analyzer doubles as the integrity-report checker: import it
# as a module so tests assert with EXACTLY the logic the CI gate runs.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_integrity", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _engine_factory(params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_blocks", 24)
    kw.setdefault("block_size", BS)
    kw.setdefault("steps_per_sched", 4)
    kw.setdefault("pipeline_depth", 2)

    def factory():
        return ServingEngine(params, CFG, temperature=0.0, **kw)

    return factory


def _undisturbed(params, prompts, n_new, **kw):
    eng = _engine_factory(params, **kw)()
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return {rids[rid]: toks for rid, toks in out.items()}


def _fleet(params, n=2, faults=None, bus=None, engine_kw=None,
           loop_kwargs=None, **router_kw):
    factory = _engine_factory(params, **(engine_kw or {}))
    reps = [
        Replica(i, factory, bus=bus, fault_injector=faults,
                loop_kwargs=loop_kwargs)
        for i in range(n)
    ]
    router_kw.setdefault("eject_backoff_s", 0.1)
    return Router(reps, bus=bus, **router_kw)


def _reference_greedy(params, prompt, n_new):
    toks = generate(
        params, CFG, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


# -- vocabulary / knob validation -------------------------------------------


def test_parse_corruption_faults():
    plan = parse_serving_faults(
        "corrupt_kv_page@req1:r0, corrupt_weights@req2, wrong_token@req1:r1"
    )
    assert plan == [
        ServingFault("corrupt_kv_page", 1, 0),
        ServingFault("corrupt_weights", 2, None),
        ServingFault("wrong_token", 1, 1),
    ]


def test_integrity_vocabulary_registered():
    for kind in ("quarantine", "drop_corrupt_block"):
        assert kind in DECISION_KINDS
    for kind in (
        "fault_fired",
        "integrity_probe",
        "integrity_quarantine",
        "integrity_kv_mismatch",
        "integrity_weight_mismatch",
        "integrity_invalid_token",
    ):
        assert kind in EVENT_KINDS


def test_knob_validation(params):
    reps = [Replica(0, _engine_factory(params))]
    with pytest.raises(ValueError, match="probe_interval_s"):
        Router(reps, probe_interval_s=-1.0)
    with pytest.raises(ValueError, match="probe_count"):
        Router(reps, probe_count=0)
    with pytest.raises(ValueError, match="probe_max_new"):
        Router(reps, probe_max_new=0)
    with pytest.raises(ValueError, match="probe_timeout_s"):
        Router(reps, probe_timeout_s=0.0)
    with pytest.raises(ValueError, match="probe_interval_s"):
        FrontendConfig(probe_interval_s=-0.5)
    with pytest.raises(ValueError, match="probe_count"):
        FrontendConfig(probe_count=0)
    with pytest.raises(ValueError, match="weight_fingerprint_interval_s"):
        FrontendConfig(weight_fingerprint_interval_s=-1.0)
    eng = _engine_factory(params, n_blocks=8)()
    with pytest.raises(ValueError, match="weight_fingerprint_interval_s"):
        EngineLoop(eng, weight_fingerprint_interval_s=-1.0)


def test_probes_refuse_sampling_engine(params):
    # Bit-exact probe comparison is meaningless against stochastic decode:
    # a sampling engine draws fresh noise per generation, so every probe
    # would diverge and quarantine healthy replicas. The router must
    # refuse at start, before pinning a baseline.
    def sampling_factory():
        return ServingEngine(params, CFG, temperature=0.8, max_batch=2,
                             n_blocks=24, block_size=BS)

    router = Router([Replica(0, sampling_factory)], probe_interval_s=0.2)
    try:
        with pytest.raises(ValueError, match="temperature"):
            router.start()
    finally:
        router.stop()


# -- probe primitives --------------------------------------------------------


def test_probe_prompts_shared_prefix():
    a = integrity.probe_prompts(4, 9, CFG.vocab_size)
    b = integrity.probe_prompts(4, 9, CFG.vocab_size)
    assert a == b  # deterministic for a fixed seed
    for p in a:
        assert len(p) == 9
        assert all(0 <= t < CFG.vocab_size for t in p)
        assert p[:-1] == a[0][:-1]  # shared prefix, last token differs
    assert len({tuple(p) for p in a}) == len(a)
    with pytest.raises(ValueError, match="n_probes"):
        integrity.probe_prompts(0, 9, CFG.vocab_size)
    with pytest.raises(ValueError, match="probe_len"):
        integrity.probe_prompts(2, 1, CFG.vocab_size)


def test_build_probe_set_pins_reference_greedy(params):
    probes = integrity.build_probe_set(params, CFG, n_probes=2, probe_len=9,
                                       max_new=4)
    again = integrity.build_probe_set(params, CFG, n_probes=2, probe_len=9,
                                      max_new=4)
    assert probes == again
    for p in probes:
        assert list(p.expected) == _reference_greedy(params, list(p.prompt), 4)
    # The pin must agree with the serving engine a healthy probe runs on:
    # greedy bit-identity between the reference path and the engine is the
    # invariant the whole sentinel rests on.
    out = _undisturbed(params, [list(p.prompt) for p in probes], 4)
    for i, p in enumerate(probes):
        assert out[i] == list(p.expected)


def test_weight_fingerprint_moves_on_corruption(params):
    eng = _engine_factory(params, n_blocks=8)()
    fp0 = integrity.weight_fingerprint(eng.params)
    assert fp0 == integrity.weight_fingerprint(eng.params)  # deterministic
    assert ServingFaultInjector._fire_corrupt_weights(eng)
    assert integrity.weight_fingerprint(eng.params) != fp0


def test_array_digest_and_verify():
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    d = integrity.array_digest(arr)
    assert d == integrity.array_digest(arr.copy())
    flipped = arr.copy()
    flipped[3, 4] += 1
    assert integrity.array_digest(flipped) != d
    # dtype and shape are part of the digest, not just the bytes
    assert integrity.array_digest(arr.reshape(4, 16)) != d
    integrity.verify_array(arr, None, "w")  # pre-checksum ckpt: vacuous
    integrity.verify_array(arr, d, "w")
    with pytest.raises(integrity.IntegrityError, match="checksum mismatch"):
        integrity.verify_array(flipped, d, "w")


def test_kv_block_digest_detects_page_flip(params):
    prompts = [p + [1, 2, 3] for p in [list(range(16))] * 2]
    eng = _engine_factory(params, prefix_cache=True)()
    for p in prompts:
        eng.submit(p, 6)
    eng.run()
    cached = eng.prefix_cache.cached_block_ids()
    assert cached
    before = {b: integrity.kv_block_digest(eng.pools, b) for b in cached}
    assert ServingFaultInjector._fire_corrupt_kv_page(eng)  # flips cached[0]
    after = {b: integrity.kv_block_digest(eng.pools, b) for b in cached}
    assert after[cached[0]] != before[cached[0]]
    for b in cached[1:]:
        assert after[b] == before[b]  # only the targeted page moved


# -- verify-on-acquire (kv_checksum) ----------------------------------------


def _shared_prefix_prompts(n, prefix_blocks=2, tail=(3, 5, 2, 6, 4, 1)):
    rng = np.random.default_rng(11)
    prefix = rng.integers(0, CFG.vocab_size, size=prefix_blocks * BS).tolist()
    out = []
    for i in range(n):
        t = int(tail[i % len(tail)])
        out.append(prefix + rng.integers(0, CFG.vocab_size, size=t).tolist())
    return out


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_verify_on_acquire_bit_identity(params, depth):
    """Flip a published shared page between two bursts: the checksum
    catches it at acquire, the block is dropped and re-prefilled
    privately, and every output stays bit-identical to cache-off — the
    corruption costs prefill work, never correctness."""
    prompts = _shared_prefix_prompts(4)
    n_new = 6
    ref = _undisturbed(params, prompts * 2, n_new,
                       pipeline_depth=depth, prefix_cache=False)

    eng = _engine_factory(params, pipeline_depth=depth, prefix_cache=True,
                          kv_checksum=True)()
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = {rids[r]: t for r, t in eng.run().items()}
    assert eng.prefix_cache.cached_block_ids()
    assert ServingFaultInjector._fire_corrupt_kv_page(eng)
    rids2 = {eng.submit(p, n_new): len(prompts) + i
             for i, p in enumerate(prompts)}
    out.update({rids2[r]: t for r, t in eng.run().items() if r in rids2})

    assert eng.stats.get("kv_mismatches", 0) >= 1
    for i in range(len(prompts) * 2):
        assert out[i] == ref[i], f"request {i} diverged past a corrupt page"
    # Allocator conservation after drain: free list + cached = everything
    # but reserved block 0 — the dropped block was freed, not leaked.
    assert eng.alloc.available + eng.prefix_cache.cached_blocks == 24 - 1


def test_drop_block_accounting():
    """drop_block in every refcount state: cold -> freed now; shared ->
    doomed, freed on final deref (never re-coldlisted); idempotent."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    hist = list(range(24))
    need = -(-len(hist) // BS)
    blocks = alloc.alloc(need)
    cache.release_row(hist, blocks, 0, len(hist))
    avail0 = alloc.available

    # Drop the chain TAIL while cold: straight back to the allocator.
    cold = cache.cached_block_ids()[-1]
    cache.drop_block(cold)
    assert alloc.available == avail0 + 1
    assert cold not in cache.cached_block_ids()

    cached, ids = cache.acquire(hist)
    assert cached == 2 * BS and len(ids) == 2  # surviving prefix still hits
    victim = ids[1]  # drop a block with a live reference
    cache.drop_block(victim)  # unreachable now, freed on final deref
    assert victim not in cache.cached_block_ids()
    avail1 = alloc.available
    cache.drop_block(victim)  # idempotent
    assert alloc.available == avail1
    cache.release_shared(ids)  # final deref frees ONLY the doomed block
    assert alloc.available == avail1 + 1  # ids[0] re-coldlisted, not freed
    # A fresh acquire can never map the dropped content again.
    cached2, ids2 = cache.acquire(hist)
    assert victim not in ids2 and cached2 == BS
    cache.release_shared(ids2)


# -- in-band token guard (satellite: reap sanity check) ----------------------


def test_wrong_token_fails_engine_before_streaming(params):
    """An out-of-vocab id at the commit point must raise — with NOTHING
    streamed for it — rather than reach a client."""
    eng = _engine_factory(params, n_blocks=8)()
    streamed = []
    eng.on_token = lambda rid, tok: streamed.append(tok)
    eng.submit(_prompts(1)[0], 6)
    assert ServingFaultInjector._fire_wrong_token(eng)
    with pytest.raises(integrity.IntegrityError, match="invalid token"):
        eng.run()
    assert eng.stats.get("invalid_tokens", 0) == 1
    assert all(0 <= t < CFG.vocab_size for t in streamed)


def test_wrong_token_redrives_bit_identical(params):
    """Fleet drill: the guard turns a corrupted commit into an engine
    failure; the router redrives every in-flight request on that replica
    and the final outputs are bit-identical to an undisturbed run (the
    garbage token was never committed, so the frontier is clean)."""
    prompts = _prompts(6)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new)
    bus = EventBus()
    events = []
    bus.subscribe(events.append)
    faults = ServingFaultInjector("wrong_token@req1:r0", bus=bus)
    router = _fleet(params, faults=faults, bus=bus)
    with router:
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], f"request {i} diverged after redrive"
    assert router.counters["redrives"] >= 1
    kinds = [e.get("event") for e in events]
    assert "integrity_invalid_token" in kinds
    assert "fault_fired" in kinds
    inv = next(e for e in events if e.get("event") == "integrity_invalid_token")
    assert inv["token"] >= CFG.vocab_size


def test_token_guard_costs_no_syncs(params, monkeypatch):
    """The guard runs on host ints the reap already materialized: device
    pulls with the guard active must EQUAL pulls with it stubbed out."""
    prompts = _prompts(4)

    def run():
        eng = _engine_factory(params, prefix_cache=True)()
        rids = {eng.submit(p, 6): i for i, p in enumerate(prompts)}
        real = np.asarray
        pulls = [0]

        def spy(a, *args, **kw):
            if isinstance(a, jax.Array):
                pulls[0] += 1
            return real(a, *args, **kw)

        monkeypatch.setattr(np, "asarray", spy)
        try:
            out = eng.run(pipeline=True)
        finally:
            monkeypatch.undo()
        return {rids[r]: t for r, t in out.items()}, pulls[0]

    out_guarded, pulls_guarded = run()
    monkeypatch.setattr(ServingEngine, "_check_token",
                        lambda self, req, tok: None)
    out_stubbed, pulls_stubbed = run()
    assert out_guarded == out_stubbed
    assert pulls_guarded == pulls_stubbed


def test_kv_digest_never_runs_with_checksum_off(params, monkeypatch):
    """kv_checksum defaults off and must cost nothing: the digest (a
    device pull per pool leaf) is never invoked."""
    calls = [0]
    real = integrity.kv_block_digest

    def counting(pools, block):
        calls[0] += 1
        return real(pools, block)

    monkeypatch.setattr(integrity, "kv_block_digest", counting)
    prompts = _shared_prefix_prompts(3)
    _undisturbed(params, prompts, 6, prefix_cache=True)
    assert calls[0] == 0
    _undisturbed(params, prompts, 6, prefix_cache=True, kv_checksum=True)
    assert calls[0] > 0  # the knob is what gates it


def test_sample_logits_nonfinite_guard():
    """Non-finite sampling-path logits return -1 (out of vocab -> the
    reap guard fails the request); finite rows are untouched and the
    legitimate -inf introduced by top-k masking does not trigger."""
    key = jax.random.key(0)
    logits = np.zeros((3, 16), dtype=np.float32)
    logits[0, 3] = 5.0
    logits[1, 1] = np.nan
    logits[2, 2] = np.inf
    out = np.asarray(sample_logits(jnp.asarray(logits), key,
                                   temperature=1.0, top_k=4))
    assert 0 <= out[0] < 16
    assert out[1] == -1 and out[2] == -1
    # Greedy path: argmax of corrupt logits still lands in vocab; the
    # golden probes own that case, the guard must not interfere.
    g = np.asarray(sample_logits(jnp.asarray(logits), key, temperature=0.0))
    assert g.shape == (3,)


# -- checkpoint checksums ----------------------------------------------------


def test_checkpoint_checksum_fallback(tmp_path):
    """A bit-flipped leaf fails restore like a torn write: load raises
    IntegrityError, restore_latest skips past it to the previous step."""
    state1 = {"w": np.arange(64, dtype=np.float32).reshape(8, 8),
              "b": np.ones(8, dtype=np.float32)}
    state2 = {"w": state1["w"] + 1.0, "b": state1["b"] * 2.0}
    ckpt.save_checkpoint(str(tmp_path), 1, state1)
    path2 = ckpt.save_checkpoint(str(tmp_path), 2, state2)

    # Round-trip first: checksums verify on clean data.
    restored, _ = ckpt.load_checkpoint(path2, state2)
    np.testing.assert_array_equal(restored["w"], state2["w"])

    # Flip one data byte in a step-2 leaf (file still parses as .npy).
    leaf = sorted(glob.glob(os.path.join(path2, "*.npy")))[-1]
    with open(leaf, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        byte = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([byte[0] ^ 0xFF]))

    with pytest.raises(integrity.IntegrityError, match="checksum mismatch"):
        ckpt.load_checkpoint(path2, state2)

    skipped = []
    got = ckpt.restore_latest(str(tmp_path), state1,
                              on_skip=lambda p, e: skipped.append((p, e)))
    assert got is not None
    state, _, step = got
    assert step == 1
    np.testing.assert_array_equal(state["w"], state1["w"])
    assert len(skipped) == 1
    assert isinstance(skipped[0][1], integrity.IntegrityError)


# -- fleet sentinel drills ---------------------------------------------------


def _wait(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_probe_sentinel_quarantines_corrupt_kv_page(params):
    """The full drill the CI gate runs: flip the probes' shared cached
    page on one replica (kv_checksum OFF, so the only signal is wrong
    output), and the sentinel must quarantine it from probe divergence
    alone — zero client requests lost, all bit-identical, and the
    offline integrity report attributes the detection."""
    prompts = _prompts(6)
    n_new = 8
    kw = dict(prefix_cache=True)
    ref = _undisturbed(params, prompts, n_new, **kw)

    bus = EventBus()
    events = []
    bus.subscribe(events.append)
    faults = ServingFaultInjector("corrupt_kv_page@req1:r0", bus=bus)
    router = _fleet(params, faults=faults, bus=bus, engine_kw=kw,
                    probe_interval_s=0.05, probe_timeout_s=60.0)
    with router:
        # Let probe #0 publish the shared prefix page on r0 — that page
        # (the lowest cached id) is what the fault will flip.
        _wait(
            lambda: (router.replicas[0].engine is not None
                     and router.replicas[0].engine.prefix_cache is not None
                     and router.replicas[0].engine.prefix_cache.cached_block_ids()),
            30.0, "probe prefix block published on r0",
        )
        # Client prompts are random (no overlap with the probe prefix), so
        # the corruption is invisible to clients — only the sentinel sees it.
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        _wait(lambda: router.counters["quarantines"] >= 1, 30.0, "quarantine")
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i]
    assert router.counters["probes"] >= 1
    assert router.counters["probe_failures"] >= 1
    quars = [d for d in router.decisions.tail() if d["decision"] == "quarantine"]
    assert quars and quars[0]["replica"] == 0
    assert "probe divergence" in quars[0]["reason"]
    # The offline analyzer joins the fired fault to its detection.
    report = obs_report.build_integrity_report(events)
    assert report["problems"] == []
    assert report["quarantines"] >= 1
    assert report["corruptions_fired"] >= 1
    det = report["detections"]
    assert det and det[0]["fault"] == "corrupt_kv_page" and det[0]["detected"]
    assert det[0]["detection_latency_s"] >= 0.0
    # Probes ran on a fresh (relaunched) replica afterwards and passed:
    # the integrity snapshot is exposed on readiness.
    snap = router._integrity_snapshot()
    assert snap["enabled"] and snap["quarantines"] >= 1


def test_weight_fingerprint_sentinel_quarantines(params):
    """In-place weight corruption: the loop-thread fingerprint drifts
    from its pinned value and the sentinel quarantines without waiting
    for a probe round-trip. Requests in flight on the corrupt replica
    during the exposure window may stream wrong tokens (that bound is
    exactly what obs_report measures) — but post-recovery traffic must
    be bit-identical again."""
    prompts = _prompts(4)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new)
    bus = EventBus()
    events = []
    bus.subscribe(events.append)
    faults = ServingFaultInjector("corrupt_weights@req1:r0", bus=bus)
    router = _fleet(
        params, faults=faults, bus=bus,
        loop_kwargs=dict(weight_fingerprint_interval_s=0.05),
        probe_interval_s=0.2, probe_timeout_s=60.0,
    )
    with router:
        trigger = [router.submit(p, n_new) for p in prompts]
        for r in trigger:
            status, _, _ = r.result(timeout=120)
            assert status == "done"  # exposure window: no identity claim
        _wait(lambda: router.counters["quarantines"] >= 1, 30.0, "quarantine")
        _wait(lambda: all(rep.accepting for rep in router.replicas), 30.0,
              "relaunch")
        # Post-recovery: fresh weights from the factory, identity restored.
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], f"post-recovery request {i} diverged"
    assert any(
        e.get("event") in ("integrity_weight_mismatch", "integrity_probe")
        and (e.get("event") != "integrity_probe" or not e.get("ok", True))
        for e in events
    )
    report = obs_report.build_integrity_report(events)
    assert report["quarantines"] >= 1
    assert report["corruptions_fired"] >= 1
    assert report["detections"] and report["detections"][0]["detected"]


def test_readyz_and_debug_surface_integrity(params):
    router = _fleet(params, probe_interval_s=0.05, probe_timeout_s=60.0)
    with router:
        _wait(
            lambda: all(
                r["ok"]
                for r in router._integrity_snapshot()["replicas"].values()
            ),
            30.0, "a passing probe on every replica",
        )
        ready = router.readiness()
        dbg = router.debug_engine()
    assert "integrity" in ready
    per = ready["integrity"]["replicas"]
    assert set(per) == {"0", "1"}
    for snap in per.values():
        assert snap["ok"] is True
        assert snap["age_s"] >= 0.0
    assert ready["integrity"]["quarantines"] == 0
    assert dbg["fleet"]["integrity"]["probes_run"] >= 2
    # Disabled by default: no integrity section, no probe threads.
    router2 = _fleet(params)
    with router2:
        assert "integrity" not in router2.readiness()
        assert router2.counters["probes"] == 0
