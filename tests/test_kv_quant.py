"""int8 KV cache: quantization math, memory halving, decode fidelity.

Serving feature beyond the reference (whose generate has no cache at all,
transformer.py:96-114): the persistent decode cache — the HBM term that
scales with L*B*T — stores int8 values + per-(token, head) fp32 amax
scales instead of bf16/fp32 elements.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.models import transformer

CFG = dataclasses.replace(
    get_preset("tiny").model, compute_dtype="float32", kv_cache_dtype="int8"
)


def test_quantize_roundtrip_error_bound():
    x = jax.random.normal(jax.random.key(0), (2, 16, 4, 8), jnp.float32) * 3.0
    q, scale = transformer._kv_quantize(x)
    assert q.dtype == jnp.int8
    back = transformer._kv_dequantize(q, scale, jnp.float32)
    # Symmetric int8: error <= half a quantization step = amax/254 per row.
    bound = np.broadcast_to(np.asarray(scale) / 254.0 + 1e-7, x.shape)
    np.testing.assert_array_less(np.abs(np.asarray(back - x)), bound)


def test_int8_cache_structure_and_memory():
    # Structure assertions target the STACKED container explicitly (the
    # model default is the unstacked per-layer tuple, same fields/leaves).
    stacked_cfg = dataclasses.replace(CFG, decode_cache_layout="stacked")
    cache = transformer.make_kv_cache(stacked_cfg, 2, 32)
    assert set(cache) == {"k", "v", "k_scale", "v_scale"}
    un = transformer.make_kv_cache(CFG, 2, 32)
    assert set(un) == {"layers"} and len(un["layers"]) == CFG.n_layers
    assert set(un["layers"][0]) == {"k", "v", "k_scale", "v_scale"}
    assert cache["k"].dtype == jnp.int8
    assert cache["k_scale"].shape == cache["k"].shape[:-1] + (1,)
    # vs bf16 cache: ~1.9x smaller at Dh=64 (1 + 4/Dh bytes vs 2 per elem).
    dense = transformer.make_kv_cache(
        dataclasses.replace(CFG, kv_cache_dtype="compute", compute_dtype="bfloat16"),
        2, 32,
    )
    int8_bytes = sum(a.nbytes for a in jax.tree.leaves(cache))
    bf16_bytes = sum(a.nbytes for a in jax.tree.leaves(dense))
    dh = CFG.head_dim
    expected = (1 + 4 / dh) / 2
    assert int8_bytes / bf16_bytes == pytest.approx(expected, rel=1e-6)


def test_int8_decode_logits_close_to_exact():
    """Prefill + per-token decode through the int8 cache tracks the exact
    uncached forward closely (per-head amax int8 is a mild perturbation)."""
    params = transformer.init_params(CFG, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 12), 0, CFG.vocab_size)
    exact, _ = transformer.forward(params, tokens, CFG)

    cache = transformer.make_kv_cache(CFG, 2, 12)
    logits_p, cache = transformer.forward(
        params, tokens[:, :6], CFG, kv_cache=cache, cache_index=jnp.int32(0)
    )
    logits = [logits_p]
    for i in range(6, 12):
        step, cache = transformer.forward(
            params, tokens[:, i : i + 1], CFG, kv_cache=cache,
            cache_index=jnp.int32(i),
        )
        logits.append(step)
    got = jnp.concatenate(logits, axis=1)
    err = float(jnp.abs(got - exact).max())
    spread = float(jnp.abs(exact).max())
    assert err < 0.05 * spread, (err, spread)
    # And the quantization is actually in play (not bit-exact).
    assert err > 0.0


@pytest.mark.parametrize("lengths", [None, [3, 8, 5]])
def test_int8_generation_matches_exact_greedy(lengths):
    """Greedy generation with the int8 cache equals the exact-cache output
    for a well-separated (trained-free random-init) tiny model — argmax is
    robust to the small quantization perturbation here; equality is checked
    for dense AND ragged batches."""
    params = transformer.init_params(CFG, jax.random.key(0))
    b = 3 if lengths else 2
    pmax = max(lengths) if lengths else 8
    prompt = jax.random.randint(jax.random.key(2), (b, pmax), 0, CFG.vocab_size)
    kw = dict(temperature=0.0)
    if lengths:
        kw["prompt_lengths"] = jnp.asarray(lengths)
    exact_cfg = dataclasses.replace(CFG, kv_cache_dtype="compute")
    want = np.asarray(generate(params, exact_cfg, prompt, 8, jax.random.key(3), **kw))
    got = np.asarray(generate(params, CFG, prompt, 8, jax.random.key(3), **kw))
    np.testing.assert_array_equal(got, want)


def test_int8_cache_rejects_explicit_dtype():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        transformer.make_kv_cache(CFG, 1, 8, dtype="float32")


def test_int8_ragged_stop_token_compose():
    """The three serving features compose: int8 cache + ragged batch +
    stop token produce exactly the exact-cache result under greedy."""
    params = transformer.init_params(CFG, jax.random.key(0))
    lengths = jnp.asarray([3, 7, 5])
    prompt = jax.random.randint(jax.random.key(4), (3, 7), 0, CFG.vocab_size)
    exact_cfg = dataclasses.replace(CFG, kv_cache_dtype="compute")
    base = np.asarray(
        generate(
            params, exact_cfg, prompt, 8, jax.random.key(5), temperature=0.0,
            prompt_lengths=lengths,
        )
    )
    stop = int(base[1, 1])  # a token actually emitted mid-stream
    want = np.asarray(
        generate(
            params, exact_cfg, prompt, 8, jax.random.key(5), temperature=0.0,
            prompt_lengths=lengths, stop_token=stop,
        )
    )
    got = np.asarray(
        generate(
            params, CFG, prompt, 8, jax.random.key(5), temperature=0.0,
            prompt_lengths=lengths, stop_token=stop,
        )
    )
    np.testing.assert_array_equal(got, want)
    # Stop semantics held somewhere: row 1 froze after its stop token.
    hits = np.where(want[1] == stop)[0]
    assert hits.size and (want[1, hits[0]:] == stop).all()
