"""Disaggregated prefill/decode tiers: KV-page migration over the wire.

The correctness bar is the fleet tests' bit-identity contract extended
across KV state crossing a process boundary: a fleet split into prefill
and decode tiers must produce greedy outputs BIT-IDENTICAL to a single
colocated engine — including when the prefill worker is killed mid-leg
(silent colocated fallback) and when a migrated page arrives corrupted
(detected by its transported digest, dropped, re-prefilled — corruption
may cost latency but never a wrong token).

Layers under test, bottom-up: the pure framing codec (split/join, torn
transfers, the wire-level frame cap), the worker's fence filter for
stale kv_page frames, snapshot/adopt against real engines (digest
parity with the acquire-side checksum algorithm), the router's
disaggregation orchestration in-process, and — marked ``slow`` like the
other subprocess drills — the same over real worker processes and TCP.
"""

import base64
import dataclasses
import os

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.frontend import kv_transfer, wire
from pretraining_llm_tpu.frontend.kv_transfer import (
    adopt_chain,
    corrupt_first_page,
    join_frames,
    snapshot_chain,
    split_frames,
)
from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.frontend.worker import WorkerServer
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector
from pretraining_llm_tpu.resilience.integrity import kv_block_digest

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _engine_factory(params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_blocks", 24)
    kw.setdefault("block_size", 8)
    kw.setdefault("steps_per_sched", 4)
    kw.setdefault("pipeline_depth", 2)

    def factory():
        return ServingEngine(params, CFG, temperature=0.0, **kw)

    return factory


def _undisturbed(params, prompts, n_new, **kw):
    eng = _engine_factory(params, **kw)()
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return {rids[rid]: toks for rid, toks in out.items()}


def _shared_prefix_prompts(n=3, shared=12, tail=3, seed=42):
    """Hot-prefix workload: migrating the shared chain once warms the
    decode tier for every sibling."""
    rng = np.random.default_rng(seed)
    head = rng.integers(0, CFG.vocab_size, size=shared).tolist()
    return [
        head + rng.integers(0, CFG.vocab_size, size=tail).tolist()
        for _ in range(n)
    ]


def _distinct_prompts(n=3, length=13, seed=7):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG.vocab_size, size=length).tolist()
        for _ in range(n)
    ]


# -- framing codec (pure python, no JAX, no engine) --------------------------


def _toy_xfer(n_pages=4, leaf_shapes=((2, 8, 4), (8,)), seed=0):
    """A synthetic transfer with real digests over random int8 pages —
    ~72 payload bytes per page with the default shapes."""
    rng = np.random.default_rng(seed)
    layout = [{"dtype": "int8", "shape": list(s)} for s in leaf_shapes]
    pages = []
    for _ in range(n_pages):
        arrays = [
            rng.integers(-128, 128, size=s, dtype=np.int8)
            for s in leaf_shapes
        ]
        pages.append({
            "digest": kv_transfer._page_digest(arrays),
            "leaves": [
                base64.b64encode(a.tobytes()).decode("ascii")
                for a in arrays
            ],
        })
    return {
        "v": kv_transfer.XFER_VERSION,
        "block_size": 8,
        "tokens": rng.integers(0, 100, size=n_pages * 8).tolist(),
        "layout": layout,
        "pages": pages,
    }


def test_split_join_roundtrip_respects_budget():
    xfer = _toy_xfer(n_pages=4)
    frames = split_frames(xfer, budget=150)  # two 72-byte pages per frame
    assert len(frames) == 2
    assert [f["seq"] for f in frames] == [0, 1]
    assert all(f["n_frames"] == 2 for f in frames)
    assert all(len(f["pages"]) == 2 for f in frames)
    # header rides frame 0 only
    assert frames[0]["tokens"] == xfer["tokens"]
    assert "tokens" not in frames[1]
    # arrival order does not matter
    assert join_frames(frames[::-1]) == xfer
    assert join_frames(frames) == xfer


def test_split_oversized_page_still_travels():
    # A single page above the budget gets a frame of its own instead of
    # being dropped; the wire-level frame cap is the real backstop.
    xfer = _toy_xfer(n_pages=3)
    frames = split_frames(xfer, budget=10)
    assert len(frames) == 3
    assert all(len(f["pages"]) == 1 for f in frames)
    assert join_frames(frames) == xfer
    with pytest.raises(ValueError, match="budget"):
        split_frames(xfer, budget=0)


def test_split_empty_transfer_keeps_header_frame():
    xfer = _toy_xfer(n_pages=1)
    xfer["pages"] = []
    xfer["tokens"] = []
    frames = split_frames(xfer)
    assert len(frames) == 1 and frames[0]["pages"] == []
    assert join_frames(frames)["pages"] == []


def test_join_torn_transfers_rejected_as_a_unit():
    frames = split_frames(_toy_xfer(n_pages=3), budget=80)
    assert len(frames) == 3
    with pytest.raises(ValueError, match="missing frames"):
        join_frames(frames[:-1])
    with pytest.raises(ValueError, match="duplicate seq"):
        join_frames(frames + [frames[1]])
    bad = [dict(f) for f in frames]
    bad[2]["n_frames"] = 4
    with pytest.raises(ValueError, match="inconsistent n_frames"):
        join_frames(bad)
    bad = [dict(f) for f in frames]
    bad[1]["seq"] = 9
    with pytest.raises(ValueError, match="bad seq"):
        join_frames(bad)
    headless = [dict(f) for f in frames]
    del headless[0]["tokens"]
    with pytest.raises(ValueError, match="header missing"):
        join_frames(headless)
    with pytest.raises(ValueError, match="no frames"):
        join_frames([])


def test_kv_page_frame_above_wire_cap_refused():
    # One page whose base64 payload alone exceeds MAX_FRAME_BYTES must
    # be refused at encode time (ProtocolError), not sent as garbage.
    frame = {
        "op": "kv_page", "seq": 0, "n_frames": 1,
        "pages": [{"digest": "0" * 32,
                   "leaves": ["A" * (wire.MAX_FRAME_BYTES + 1)]}],
    }
    with pytest.raises(wire.ProtocolError, match="exceeds MAX_FRAME_BYTES"):
        wire.encode_frame(frame)


def test_corrupt_first_page_breaks_digest_only():
    xfer = _toy_xfer(n_pages=2)
    before = [
        {"digest": p["digest"], "leaves": list(p["leaves"])}
        for p in xfer["pages"]
    ]
    assert corrupt_first_page(xfer)
    # exactly one byte of page 0 leaf 0 flipped; digest still claims the
    # ORIGINAL bytes (that lie is what the receiver must catch)
    raw0 = base64.b64decode(before[0]["leaves"][0])
    raw1 = base64.b64decode(xfer["pages"][0]["leaves"][0])
    assert raw1[0] == raw0[0] ^ 0xFF and raw1[1:] == raw0[1:]
    assert xfer["pages"][0]["digest"] == before[0]["digest"]
    assert xfer["pages"][1] == before[1]
    assert not corrupt_first_page({"pages": []})


def test_worker_drops_stale_fence_kv_pages():
    # The fence filter runs before any frame is accumulated, so a page
    # push racing a redrive fence bump can never poison the pool. Bare
    # WorkerServer: the stale path touches only fence/rx bookkeeping.
    ws = WorkerServer.__new__(WorkerServer)
    ws._fence = 3
    ws._kv_rx = {}
    ws._kv_stale_frames = 0
    sent = []
    ws._send = lambda payload, g=None: sent.append(payload)
    # interior frame at the current generation accumulates silently
    ws._handle_kv_page(
        {"xfer": "x1", "g": 3, "seq": 0, "n_frames": 2, "pages": []}
    )
    assert "x1" in ws._kv_rx and not sent
    # stale FINAL frame: the whole partial transfer is dropped and the
    # sender told why
    ws._handle_kv_page(
        {"xfer": "x1", "g": 2, "id": 7, "seq": 1, "n_frames": 2,
         "pages": []}
    )
    assert ws._kv_rx == {} and ws._kv_stale_frames == 1
    assert sent[-1] == {
        "id": 7, "error": "stale_fence",
        "message": sent[-1]["message"],
    }
    assert "predates fence 3" in sent[-1]["message"]
    # stale interior frame: dropped without a reply (nothing to nack)
    sent.clear()
    ws._handle_kv_page(
        {"xfer": "x2", "g": 0, "seq": 0, "n_frames": 1, "pages": []}
    )
    assert ws._kv_rx == {} and not sent and ws._kv_stale_frames == 2


# -- snapshot/adopt against real engines -------------------------------------


_KV_KW = {"prefix_cache": True, "kv_checksum": True}


def _warm_engine(params, prompt, n_new=4, **kw):
    eng = _engine_factory(params, **{**_KV_KW, **kw})()
    eng.submit(prompt, n_new)
    eng.run()
    return eng


def test_snapshot_digest_parity_with_acquire_side_checksum(params):
    # The transported digest must be byte-for-byte the kv_block_digest
    # the receiver's verify-on-acquire recomputes, or every migrated
    # page would look corrupt on first local hit.
    prompt = _distinct_prompts(1, length=20)[0]
    eng = _warm_engine(params, prompt)
    xfer = snapshot_chain(eng, prompt)
    assert xfer is not None and len(xfer["pages"]) == 2  # (20-1)//8 blocks
    assert xfer["block_size"] == 8
    assert xfer["tokens"] == prompt[:16]
    _, blocks = eng.prefix_cache.acquire(prompt)
    try:
        assert len(blocks) == 2
        for page, b in zip(xfer["pages"], blocks):
            assert page["digest"] == kv_block_digest(eng.pools, b)
            assert page["digest"] == eng.prefix_cache.checksum_of(b)
    finally:
        eng.prefix_cache.release_shared(blocks)


def test_snapshot_without_cache_or_coverage_is_none(params):
    prompt = _distinct_prompts(1, length=20)[0]
    nocache = _engine_factory(params, prefix_cache=False)()
    assert snapshot_chain(nocache, prompt) is None
    cold = _engine_factory(params, **_KV_KW)()
    assert snapshot_chain(cold, prompt) is None  # nothing cached yet


def test_adopt_roundtrip_is_bit_identical(params):
    prompt = _distinct_prompts(1, length=20)[0]
    n_new = 6
    ref = _undisturbed(params, [prompt], n_new, **_KV_KW)
    src = _warm_engine(params, prompt, n_new=n_new)
    xfer = snapshot_chain(src, prompt)
    dst = _engine_factory(params, **_KV_KW)()
    res = adopt_chain(dst, xfer)
    assert res == {
        "inserted": 2, "rejected": 0, "published": 2, "reason": "",
    }
    assert dst.stats["kv_pages_adopted"] == 2
    assert dst.stats.get("kv_pages_rejected", 0) == 0
    # re-adopting the same chain publishes nothing new (first writer
    # wins; the duplicate blocks go straight back to the allocator)
    res2 = adopt_chain(dst, snapshot_chain(src, prompt))
    assert res2["inserted"] == 2 and res2["published"] == 0
    # decoding on the warmed receiver reproduces the reference exactly
    rid = dst.submit(prompt, n_new)
    assert dst.run()[rid] == ref[0]


def test_adopt_rejects_are_typed_and_counted(params):
    prompt = _distinct_prompts(1, length=20)[0]
    src = _warm_engine(params, prompt)
    xfer = snapshot_chain(src, prompt)

    nocache = _engine_factory(params, prefix_cache=False)()
    res = adopt_chain(nocache, dict(xfer))
    assert res["inserted"] == 0 and res["reason"] == "no_prefix_cache"
    assert nocache.stats["kv_pages_rejected"] == 2

    wrong_bs = _engine_factory(params, block_size=16, **_KV_KW)()
    res = adopt_chain(wrong_bs, dict(xfer))
    assert res["reason"] == "block_size_mismatch" and res["rejected"] == 2

    dst = _engine_factory(params, **_KV_KW)()
    res = adopt_chain(dst, {**xfer, "v": 99})
    assert res["reason"] == "version_mismatch" and res["inserted"] == 0


def test_adopt_truncates_chain_at_first_corrupt_page(params):
    prompt = _distinct_prompts(1, length=20)[0]
    n_new = 6
    ref = _undisturbed(params, [prompt], n_new, **_KV_KW)
    src = _warm_engine(params, prompt, n_new=n_new)

    # page 0 corrupt: nothing adoptable
    xfer = snapshot_chain(src, prompt)
    assert corrupt_first_page(xfer)
    dst = _engine_factory(params, **_KV_KW)()
    res = adopt_chain(dst, xfer)
    assert res == {
        "inserted": 0, "rejected": 2, "published": 0,
        "reason": "checksum_mismatch",
    }
    assert dst.stats["kv_pages_rejected"] == 2

    # page 1 corrupt: the clean prefix (page 0) is adopted, the rest
    # dropped — and decoding on the receiver is STILL bit-identical,
    # because the dropped span simply re-prefills
    xfer = snapshot_chain(src, prompt)
    raw = bytearray(base64.b64decode(xfer["pages"][1]["leaves"][0]))
    raw[0] ^= 0xFF
    xfer["pages"][1]["leaves"][0] = base64.b64encode(bytes(raw)).decode()
    dst = _engine_factory(params, **_KV_KW)()
    res = adopt_chain(dst, xfer)
    assert res == {
        "inserted": 1, "rejected": 1, "published": 1,
        "reason": "checksum_mismatch",
    }
    assert dst.stats["kv_pages_adopted"] == 1
    assert dst.stats["kv_pages_rejected"] == 1
    rid = dst.submit(prompt, n_new)
    assert dst.run()[rid] == ref[0]


# -- in-process disaggregated fleet (router orchestration) -------------------


def _disagg_fleet(params, faults=None, bus=None, engine_kw=None, **router_kw):
    factory = _engine_factory(params, **{**_KV_KW, **(engine_kw or {})})
    reps = [
        Replica(0, factory, role="prefill", bus=bus, fault_injector=faults),
        Replica(1, factory, role="decode", bus=bus, fault_injector=faults),
    ]
    router_kw.setdefault("eject_backoff_s", 0.1)
    return Router(reps, bus=bus, **router_kw)


@pytest.mark.parametrize("depth", [1, 2, 3])
@pytest.mark.parametrize("cache", [False, True], ids=["nocache", "cache"])
def test_disagg_bit_identity_grid(params, depth, cache):
    kw = {
        "pipeline_depth": depth, "prefix_cache": cache,
        "kv_checksum": cache,
    }
    prompts = _shared_prefix_prompts(3)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new, **kw)
    router = _disagg_fleet(params, engine_kw=kw)
    with router:
        results = []
        for p in prompts:  # serial: deterministic migration/warmth order
            results.append(router.submit(p, n_new).result(timeout=120))
    reps = router.replicas
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], (i, tokens, ref[i])
        # the prefill tier never serves client traffic
        assert info["replica"] == 1
    if cache:
        # the shared chain migrated once; the siblings found the decode
        # tier already warm and skipped the wire entirely
        assert router.counters["kv_migrations"] == 1
        assert router.counters["kv_pages_migrated"] >= 1
        assert router.counters["kv_migration_rejects"] == 0
        assert reps[1].engine.stats["kv_pages_adopted"] >= 1
    else:
        # nothing snapshotable without a prefix cache: legs run but no
        # page ever crosses, and outputs are unaffected either way
        assert router.counters["kv_pages_migrated"] == 0


def test_corrupt_kv_migration_never_serves_wrong_tokens(params):
    # The drill: the fault injector flips one byte of the first migrated
    # page while its digest still claims the original bytes. The decode
    # tier must detect, drop, re-prefill — outputs stay bit-identical
    # and the drop is visible as counters + a typed reject event.
    prompts = _distinct_prompts(3)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new, **_KV_KW)
    events = []
    bus = EventBus()
    bus.subscribe(lambda ev: events.append(ev))
    faults = ServingFaultInjector("corrupt_kv_migration@req1:r1", bus=bus)
    router = _disagg_fleet(params, faults=faults, bus=bus)
    with router:
        results = []
        for p in prompts:
            results.append(router.submit(p, n_new).result(timeout=120))
    reps = router.replicas
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], (i, tokens, ref[i])
    assert router.counters["kv_migrations"] == 3
    assert router.counters["kv_migration_rejects"] >= 1
    assert reps[1].engine.stats["kv_pages_rejected"] >= 1
    assert reps[1].engine.stats["kv_pages_adopted"] >= 1
    kinds = [ev.get("event") for ev in events]
    assert "kv_migrate" in kinds
    assert "fault_fired" in kinds
    rejects = [ev for ev in events if ev.get("event") == "kv_migration_reject"]
    assert rejects and rejects[0]["reason"] == "checksum_mismatch"
    assert rejects[0]["replica"] == 1
    counts = router.decisions.counts_snapshot()
    assert counts.get("kv_migrate") == 3
    assert counts.get("kv_migration_reject", 0) >= 1


def test_prefill_fetch_failure_falls_back_colocated(params):
    # A prefill tier that dies between the leg and the page pull costs
    # nothing but the wasted leg: the decode tier re-prefills.
    prompts = _distinct_prompts(2)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new, **_KV_KW)
    router = _disagg_fleet(params)

    def boom(prompt, **kw):
        raise RuntimeError("prefill tier vanished")

    router.replicas[0].fetch_kv_pages = boom
    with router:
        results = []
        for p in prompts:
            results.append(router.submit(p, n_new).result(timeout=120))
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], (i, tokens, ref[i])
    assert router.counters["kv_pages_migrated"] == 0


def test_single_tier_fleet_never_migrates(params):
    # No replica advertises role=prefill: the disaggregation path must
    # stay entirely cold (zero legs, zero counters).
    prompts = _distinct_prompts(2)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new, **_KV_KW)
    factory = _engine_factory(params, **_KV_KW)
    reps = [Replica(i, factory) for i in range(2)]
    router = Router(reps, eject_backoff_s=0.1)
    with router:
        results = [
            router.submit(p, n_new).result(timeout=120) for p in prompts
        ]
    for i, (status, tokens, _info) in enumerate(results):
        assert status == "done" and tokens == ref[i]
    assert router.counters["kv_migrations"] == 0


# -- subprocess drills: real workers, real TCP -------------------------------


def _worker_spec(role, **extra):
    spec = {
        "preset": "tiny",
        "init_seed": 0,
        "model_overrides": {"compute_dtype": "float32"},
        "engine": {
            "max_batch": 2, "n_blocks": 24, "block_size": 8,
            "steps_per_sched": 4, "pipeline_depth": 2,
            "prefix_cache": True, "kv_checksum": True,
        },
        "admission": {"max_queue_depth": 8},
        "role": role,
    }
    spec.update(extra)
    return spec


@pytest.mark.slow
def test_process_disagg_bit_identity(params):
    prompts = _shared_prefix_prompts(3, tail=3)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new, **_KV_KW)
    reps = [
        RemoteReplica(0, _worker_spec("prefill")),
        RemoteReplica(1, _worker_spec("decode")),
    ]
    router = Router(reps, eject_backoff_s=60.0)
    with router:
        assert reps[0].role == "prefill" and reps[1].role == "decode"
        assert reps[0].kv_capable and reps[1].kv_capable
        results = []
        for p in prompts:
            results.append(router.submit(p, n_new).result(timeout=120))
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], (i, tokens, ref[i])
        assert info["replica"] == 1
    assert router.counters["kv_migrations"] >= 1
    assert router.counters["kv_pages_migrated"] >= 1
    assert router.counters["kv_migration_rejects"] == 0


@pytest.mark.slow
def test_process_prefill_kill_mid_leg_falls_back(params):
    # The prefill worker SIGKILLs itself right after acking its FIRST
    # wire submit — which is request 0's prefill leg, mid-migration.
    # Both requests must still finish bit-identically on the decode
    # tier; the dead prefill tier just means no pages ever cross.
    prompts = _distinct_prompts(2, seed=3)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new, **_KV_KW)
    reps = [
        RemoteReplica(0, _worker_spec("prefill", kill_after_submits=1)),
        RemoteReplica(1, _worker_spec("decode")),
    ]
    router = Router(reps, eject_backoff_s=60.0)
    with router:
        results = []
        for p in prompts:
            results.append(router.submit(p, n_new).result(timeout=120))
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], (i, tokens, ref[i])
    assert router.counters["kv_pages_migrated"] == 0


@pytest.mark.slow
def test_process_corrupt_kv_migration_over_tcp(params):
    # Same corruption drill as in-process, but the page crosses a real
    # socket: the parent-side injector flips the byte as the transfer
    # leaves, the WORKER's adopt path catches the digest lie.
    prompts = _distinct_prompts(3)
    n_new = 8
    ref = _undisturbed(params, prompts, n_new, **_KV_KW)
    events = []
    bus = EventBus()
    bus.subscribe(lambda ev: events.append(ev))
    faults = ServingFaultInjector("corrupt_kv_migration@req1:r1", bus=bus)
    reps = [
        RemoteReplica(0, _worker_spec("prefill"), bus=bus,
                      fault_injector=faults),
        RemoteReplica(1, _worker_spec("decode"), bus=bus,
                      fault_injector=faults),
    ]
    router = Router(reps, bus=bus, eject_backoff_s=60.0)
    with router:
        results = []
        for p in prompts:
            results.append(router.submit(p, n_new).result(timeout=120))
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], (i, tokens, ref[i])
    assert router.counters["kv_migrations"] == 3
    assert router.counters["kv_migration_rejects"] >= 1
    rejects = [ev for ev in events if ev.get("event") == "kv_migration_reject"]
    assert rejects and rejects[0]["reason"] == "checksum_mismatch"
