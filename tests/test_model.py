"""Model layer: shapes, param-count parity, numerics vs a hand reference."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.models.layers import apply_rope, rope_table
from pretraining_llm_tpu.utils.pytree import tree_num_params

TINY = get_preset("tiny").model


def _fp32(cfg: ModelConfig) -> ModelConfig:
    return dataclasses.replace(cfg, compute_dtype="float32")


def test_forward_shapes():
    params = transformer.init_params(TINY, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, TINY.context_length), 0, TINY.vocab_size)
    logits, cache = transformer.forward(params, tokens, TINY)
    assert logits.shape == (2, TINY.context_length, TINY.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None


@pytest.mark.parametrize(
    "preset", ["tiny", "gpt2-124m", "llama-1b", "reference-3b", "gpt2-8k-sp"]
)
def test_param_count_matches_analytic(preset):
    cfg = get_preset(preset).model
    # Shrink to a countable size but keep the structural flags.
    small = dataclasses.replace(
        cfg,
        vocab_size=128,
        context_length=32,
        d_model=16,
        n_heads=2,
        n_layers=3,
        d_head=None,
    )
    params = transformer.init_params(small, jax.random.key(0))
    assert tree_num_params(params) == small.num_params()


def test_loss_at_init_near_uniform():
    cfg = _fp32(TINY)
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.key(2), (4, 32), 0, cfg.vocab_size)
    loss = transformer.loss_fn(params, tokens, targets, cfg)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 0.5


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = _fp32(TINY)
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab_size)
    logits1, _ = transformer.forward(params, tokens, cfg)
    tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % cfg.vocab_size)
    logits2, _ = transformer.forward(params, tokens2, cfg)
    np.testing.assert_allclose(logits1[0, :10], logits2[0, :10], atol=1e-5)
    assert not np.allclose(logits1[0, 10:], logits2[0, 10:], atol=1e-5)


def test_forward_matches_hand_reference():
    """One-block fp32 model vs an independent numpy implementation."""
    cfg = ModelConfig(
        vocab_size=31,
        context_length=8,
        d_model=16,
        n_heads=2,
        n_layers=1,
        activation="relu",
        norm="layernorm",
        pos_embed="learned",
        use_output_proj=False,
        tie_embeddings=False,
        lm_head_bias=True,
        qkv_bias=False,
        mlp_bias=True,
        compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)
    got, _ = transformer.forward(params, tokens, cfg)

    p = jax.tree.map(np.asarray, params)
    x = p["tok_embed"]["embedding"][np.asarray(tokens)] + p["pos_embed"]["embedding"][None, :8]

    def ln(v, scale, bias):
        mu = v.mean(-1, keepdims=True)
        var = v.var(-1, keepdims=True)
        return (v - mu) / np.sqrt(var + cfg.norm_eps) * scale + bias

    blk = jax.tree.map(lambda a: a[0], p["blocks"])  # unstack layer 0
    h = ln(x, blk["ln1"]["scale"], blk["ln1"]["bias"])
    qkv = np.einsum("btd,dchn->bcthn", h, blk["attn"]["wqkv"])
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(cfg.head_dim)
    mask = np.tril(np.ones((8, 8), bool))
    scores = np.where(mask[None, None], scores, -np.inf)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    attn = np.einsum("bhqk,bkhd->bqhd", probs, v).reshape(2, 8, cfg.d_model)
    x = x + attn
    h = ln(x, blk["ln2"]["scale"], blk["ln2"]["bias"])
    hidden = np.maximum(h @ blk["mlp"]["w1"] + blk["mlp"]["b1"], 0)
    x = x + hidden @ blk["mlp"]["w2"] + blk["mlp"]["b2"]
    x = ln(x, p["final_norm"]["scale"], p["final_norm"]["bias"])
    want = x @ p["lm_head"]["kernel"] + p["lm_head"]["bias"]

    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_rope_properties():
    cos, sin = rope_table(16, 8, 10000.0)
    x = jax.random.normal(jax.random.key(0), (1, 16, 2, 8))
    pos = jnp.arange(16)
    rotated = apply_rope(x, cos, sin, pos)
    # Norm-preserving per pair
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(rotated), axis=-1),
        rtol=1e-5,
    )
    # Position 0 is identity
    np.testing.assert_allclose(np.asarray(rotated[:, 0]), np.asarray(x[:, 0]), atol=1e-6)


def test_rope_relative_dot_products():
    """q.k after RoPE depends only on relative distance."""
    cos, sin = rope_table(32, 8, 10000.0)
    q = jax.random.normal(jax.random.key(0), (1, 1, 1, 8))
    k = jax.random.normal(jax.random.key(1), (1, 1, 1, 8))
    q_rep = jnp.tile(q, (1, 32, 1, 1))
    k_rep = jnp.tile(k, (1, 32, 1, 1))
    pos = jnp.arange(32)
    qr = np.asarray(apply_rope(q_rep, cos, sin, pos))
    kr = np.asarray(apply_rope(k_rep, cos, sin, pos))
    d1 = (qr[0, 5, 0] * kr[0, 3, 0]).sum()
    d2 = (qr[0, 25, 0] * kr[0, 23, 0]).sum()
    np.testing.assert_allclose(d1, d2, rtol=1e-4)


def test_swiglu_rmsnorm_rope_variant_runs():
    cfg = ModelConfig(
        vocab_size=64,
        context_length=16,
        d_model=32,
        n_heads=4,
        n_layers=2,
        activation="swiglu",
        norm="rmsnorm",
        pos_embed="rope",
        tie_embeddings=False,
        mlp_bias=False,
        compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
    logits, _ = transformer.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize(
    "policy", ["full", "dots_saveable", "save_attn", "save_attn_res",
               "save_qkv_attn", "save_big"]
)
def test_remat_matches_no_remat(policy):
    """Every remat policy is a pure scheduling choice: identical gradients.

    The named-saveable policies (save_attn / save_qkv_attn / save_big) rely
    on checkpoint_name tags inside the attention and MLP blocks; this pins
    the tags to the math staying equivalent.
    """
    cfg = _fp32(TINY)
    cfg_remat = dataclasses.replace(cfg, remat=policy)
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    g1 = jax.grad(transformer.loss_fn)(params, tokens, targets, cfg)
    g2 = jax.grad(transformer.loss_fn)(params, tokens, targets, cfg_remat)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5), g1, g2
    )


def test_return_hidden_activations():
    """Feature-extraction hook (reference forward_embedding equivalent)."""
    cfg = _fp32(TINY)
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits, cache, hidden = transformer.forward(params, tokens, cfg, return_hidden=True)
    assert hidden["block_outputs"].shape == (cfg.n_layers, 2, 16, cfg.d_model)
    assert hidden["final_hidden"].shape == (2, 16, cfg.d_model)
    # The last block output, final-normed, produces the same logits path.
    logits2, _ = transformer.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), atol=1e-6)


def test_llama_variant_kv_cache_decode_matches_full():
    """RoPE + cache positions: incremental decode == full forward (llama path)."""
    cfg = ModelConfig(
        vocab_size=64, context_length=32, d_model=32, n_heads=4, n_layers=2,
        activation="swiglu", norm="rmsnorm", pos_embed="rope",
        tie_embeddings=False, mlp_bias=False, compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 12), 0, 64)
    full_logits, _ = transformer.forward(params, tokens, cfg)
    cache = transformer.make_kv_cache(cfg, 1, 12, dtype="float32")
    logits_p, cache = transformer.forward(
        params, tokens[:, :6], cfg, kv_cache=cache, cache_index=jnp.int32(0)
    )
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(full_logits[:, :6]), rtol=2e-4, atol=2e-4
    )
    for i in range(6, 12):
        step_logits, cache = transformer.forward(
            params, tokens[:, i : i + 1], cfg, kv_cache=cache, cache_index=jnp.int32(i)
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, i]),
            rtol=2e-4, atol=2e-4,
        )
