"""MoE layer: routing numerics, capacity behavior, expert-parallel training.

Beyond-parity coverage (the reference has only a dense MLP, mlp.py:24-26).
The key numeric check: with k = n_experts and unbounded capacity, token-choice
top-k routing degenerates to a softmax-weighted mixture of all experts, which
we compare against a direct per-expert loop.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.models import moe, transformer
from pretraining_llm_tpu.training import train_step as ts


def _moe_cfg(**kw):
    base = dict(
        vocab_size=97,
        context_length=32,
        d_model=32,
        n_heads=4,
        n_layers=2,
        n_experts=4,
        experts_per_token=2,
        param_dtype="float32",
        compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


def test_moe_param_count_matches_analytic():
    cfg = _moe_cfg()
    params = transformer.init_params(cfg, jax.random.key(0))
    actual = sum(np.prod(p.shape) for p in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_moe_param_count_matches_analytic_swiglu():
    cfg = _moe_cfg(activation="swiglu", mlp_bias=False, tie_embeddings=False)
    params = transformer.init_params(cfg, jax.random.key(0))
    actual = sum(np.prod(p.shape) for p in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_active_params_counts_only_routed_experts():
    cfg = _moe_cfg(n_experts=4, experts_per_token=2)
    dense = _moe_cfg(n_experts=0)
    # Active params = dense model + router + one extra active expert FFN.
    per_expert = cfg.d_model * cfg.d_ff * 2 + cfg.d_ff + cfg.d_model
    router = cfg.d_model * cfg.n_experts
    expected = dense.num_params() + cfg.n_layers * (router + per_expert)
    assert cfg.num_active_params() == expected
    assert cfg.num_active_params() < cfg.num_params()
    assert dense.num_active_params() == dense.num_params()
    # MFU math uses active params, so MoE FLOPs/token ~ top-k not n_experts.
    assert cfg.flops_per_token() < 6 * cfg.num_params()


def test_forward_finite_and_shaped():
    cfg = _moe_cfg()
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.context_length), 0, cfg.vocab_size)
    logits, _, aux = transformer.forward(params, tokens, cfg, return_aux=True)
    assert logits.shape == (2, cfg.context_length, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert np.isfinite(float(aux))


def test_full_routing_equals_dense_mixture():
    """k = E with ample capacity => output is the softmax-weighted expert sum."""
    cfg = _moe_cfg(n_experts=4, experts_per_token=4, expert_capacity_factor=8.0)
    key = jax.random.key(0)
    mlp = moe.init_moe_params(cfg, key, resid_std=0.02, dtype=jnp.float32)
    h = jax.random.normal(jax.random.key(1), (2, 8, cfg.d_model), jnp.float32)

    out, _ = moe.moe_mlp(mlp, h, cfg)

    # Direct computation: softmax(router) over ALL experts, dense expert FFNs.
    x = h.reshape(-1, cfg.d_model)
    probs = jax.nn.softmax(x @ mlp["router"], axis=-1)  # (S, E)
    w1, w2 = mlp["experts"]["w1"], mlp["experts"]["w2"]
    b1, b2 = mlp["experts"]["b1"], mlp["experts"]["b2"]
    expected = jnp.zeros_like(x)
    for e in range(cfg.n_experts):
        hidden = jax.nn.gelu(x @ w1[e] + b1[e], approximate=True)
        expected = expected + probs[:, e : e + 1] * (hidden @ w2[e] + b2[e])
    np.testing.assert_allclose(
        np.asarray(out.reshape(-1, cfg.d_model)), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_tiny_capacity_drops_but_stays_finite():
    cfg = _moe_cfg(expert_capacity_factor=0.05)
    mlp = moe.init_moe_params(cfg, jax.random.key(0), resid_std=0.02, dtype=jnp.float32)
    h = jax.random.normal(jax.random.key(1), (2, 16, cfg.d_model), jnp.float32)
    out, aux = moe.moe_mlp(mlp, h, cfg)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(float(aux))
    # Capacity 0.05 * 2 * 32 / 4 < 1 -> clamped to 1 slot per expert: at most
    # E slots filled, so most tokens' MoE output is exactly zero.
    flat = np.asarray(out.reshape(-1, cfg.d_model))
    nonzero_tokens = (np.abs(flat).max(axis=-1) > 0).sum()
    assert nonzero_tokens <= cfg.n_experts * 1 * 2  # k slots may double-serve a token


def test_aux_loss_near_one_at_init():
    """Near-uniform router at init => Switch aux loss ~= 1."""
    cfg = _moe_cfg()
    mlp = moe.init_moe_params(cfg, jax.random.key(0), resid_std=0.02, dtype=jnp.float32)
    h = jax.random.normal(jax.random.key(1), (4, 32, cfg.d_model), jnp.float32)
    _, aux = moe.moe_mlp(mlp, h, cfg)
    assert 0.8 < float(aux) < 1.3


def test_grads_flow_to_router_and_experts():
    cfg = _moe_cfg()
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.context_length), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    grads = jax.grad(transformer.loss_fn)(params, tokens, targets, cfg)
    blk = grads["blocks"]["mlp"]
    assert float(jnp.abs(blk["router"]).max()) > 0
    assert float(jnp.abs(blk["experts"]["w1"]).max()) > 0
    assert float(jnp.abs(blk["experts"]["w2"]).max()) > 0
    assert np.isfinite(float(jnp.abs(blk["router"]).max()))


def test_grouped_routing_matches_global_when_capacity_ample():
    """With no capacity contention, per-group routing == one global pool:
    token-choice decisions are independent per token, so splitting the
    capacity pool only matters when drops occur."""
    cfg_global = _moe_cfg(expert_capacity_factor=8.0, moe_group_size=0)
    cfg_grouped = dataclasses.replace(cfg_global, moe_group_size=16)
    mlp = moe.init_moe_params(cfg_global, jax.random.key(0), resid_std=0.02, dtype=jnp.float32)
    h = jax.random.normal(jax.random.key(1), (4, 16, cfg_global.d_model), jnp.float32)
    out_global, aux_global = moe.moe_mlp(mlp, h, cfg_global)
    out_grouped, aux_grouped = moe.moe_mlp(mlp, h, cfg_grouped)
    assert moe._group_count(4 * 16, 16) == 4  # actually exercising groups
    np.testing.assert_allclose(
        np.asarray(out_grouped), np.asarray(out_global), rtol=1e-5, atol=1e-6
    )
    # Aux is computed per group then averaged (the Switch formulation —
    # balance is enforced within every group): close to, but not bit-equal
    # with, the single global pool's value.
    np.testing.assert_allclose(float(aux_grouped), float(aux_global), rtol=2e-2)


def test_group_count_mesh_independent_and_divisor():
    assert moe._group_count(32768, 2048) == 16
    assert moe._group_count(1000, 2048) == 1
    assert moe._group_count(1000, 300) == 2  # rounds down to a divisor
    assert moe._group_count(4096, 0) == 1


def test_decode_routing_is_batch_composition_independent():
    """decode=True routes without a capacity bound: a token's MoE output must
    not depend on which other sequences are co-batched (the capacity-drop
    inconsistency the training-time bound would introduce)."""
    cfg = _moe_cfg(expert_capacity_factor=0.05)  # starved at train time
    mlp = moe.init_moe_params(cfg, jax.random.key(0), resid_std=0.02, dtype=jnp.float32)
    row = jax.random.normal(jax.random.key(1), (1, 1, cfg.d_model), jnp.float32)
    other_a = jax.random.normal(jax.random.key(2), (3, 1, cfg.d_model), jnp.float32)
    other_b = jax.random.normal(jax.random.key(3), (3, 1, cfg.d_model), jnp.float32)
    out_a, _ = moe.moe_mlp(mlp, jnp.concatenate([row, other_a]), cfg, decode=True)
    out_b, _ = moe.moe_mlp(mlp, jnp.concatenate([row, other_b]), cfg, decode=True)
    # Slot assignment order differs with batch composition; values agree up
    # to summation-order noise.
    np.testing.assert_allclose(np.asarray(out_a[0]), np.asarray(out_b[0]), rtol=1e-5, atol=1e-8)
    # And nothing is dropped in decode: output is a full top-k mixture.
    assert float(jnp.abs(out_a[0]).max()) > 0


def test_moe_real_batch_dispatch_compiles_within_memory(mesh_exp4):
    """moe-8x350m at its real token count (32k tokens/step): the grouped
    dispatch must keep per-step temp memory bounded (the global-capacity
    dispatch was O(S^2) ~ 10 GB of fp32 at this batch)."""
    preset = get_preset("moe-8x350m")
    cfg = preset.replace(
        model=dataclasses.replace(preset.model, n_layers=2, remat="full"),
        mesh=dataclasses.replace(preset.mesh, data=2, fsdp=1, expert=4),
    )
    b, t = cfg.train.batch_size, cfg.model.context_length
    assert b * t >= 32768, "preset shrank: test no longer covers the real batch"
    state = ts.init_train_state(cfg, jax.random.key(0))
    state = ts.shard_train_state(state, mesh_exp4)
    x = jnp.zeros((b, t), jnp.int32)
    # Compile only (CPU execution at 32k tokens x 8 experts is minutes).
    from pretraining_llm_tpu.parallel.sharding import activation_mesh
    from pretraining_llm_tpu.models import transformer as tf

    def loss(params, xb, yb):
        with activation_mesh(mesh_exp4):
            return tf.loss_fn(params, xb, yb, cfg.model)

    compiled = jax.jit(jax.grad(loss)).lower(state["params"], x, x).compile()
    temp_gb = compiled.memory_analysis().temp_size_in_bytes / 2**30
    # Aggregate across the 8 virtual devices; the old dispatch alone was
    # ~10 GB fp32 per layer-pair. Generous bound to stay hardware-agnostic.
    assert temp_gb < 24, f"temp {temp_gb:.1f} GB: grouped dispatch regressed"


def test_expert_parallel_train_step_matches_single_device(mesh_exp4):
    """Same step on a 2-data x 4-expert mesh and on one device => same loss."""
    cfg = get_preset("tiny").replace(
        model=dataclasses.replace(
            get_preset("tiny").model,
            n_experts=4,
            experts_per_token=2,
            expert_capacity_factor=4.0,  # ample: no drops => mesh-invariant
        ),
    )
    cfg = cfg.replace(
        mesh=dataclasses.replace(cfg.mesh, data=2, expert=4),
        train=dataclasses.replace(cfg.train, batch_size=8, microbatches=1),
    )
    x = jax.random.randint(jax.random.key(1), (8, cfg.model.context_length), 0,
                           cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)

    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh_exp4)
    step = ts.build_train_step(cfg, mesh_exp4)
    sharded, metrics = step(sharded, (x, y))
    sharded_loss = float(metrics["loss"])

    single_step = ts.build_train_step(cfg, mesh=None)
    state, metrics1 = single_step(state, (x, y))
    # bf16 compute + mesh-dependent reduction order => small numeric slack
    np.testing.assert_allclose(sharded_loss, float(metrics1["loss"]), rtol=1e-3)
    assert int(jax.device_get(sharded["step"])) == 1
