"""Multi-host fleet: remote worker attach, lease-fenced partition
tolerance, and the crash-recoverable router control plane.

The correctness bar is test_process_fleet.py's, extended to faults a
real network brings that PR 12's connection-loss model cannot express:

- a silent PARTITION (no RST, no EOF — reads hang, writes buffer) must
  be detected by the heartbeat lease, its requests redriven to a
  survivor bit-identically, and the frames the blackholed worker
  streamed into the void must arrive after heal stamped with a stale
  fence generation — counted and DROPPED, never delivered twice;
- a pre-spawned ``worker.py --listen`` worker must refuse attaches
  with a bad token or the wrong engine fingerprint, survive a router
  detach, and serve the next attach;
- a router CRASH (no shutdown, no terminals — just gone) must be
  recoverable from the write-ahead fleet journal: a new router
  re-attaches the still-live workers, fences the old generation, and
  finishes every journaled in-flight request exactly once with greedy
  output bit-identical to an undisturbed run, at every pipeline depth,
  prefix cache on or off.

Workers build their own params from (preset, init_seed) — the same
``init_params(cfg, key(0))`` this module's reference engine uses — so
bit-identity assertions compare real decode output across processes.

The wire/journal/config unit tests are tier-1 (no JAX, no subprocess);
the attach/partition/restart drills spawn real worker processes and
build engines, so they are marked ``slow`` and run in ``ci_smoke.sh``.
"""

import dataclasses
import importlib.util
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.journal import FleetJournal
from pretraining_llm_tpu.frontend.loadgen import FleetAction
from pretraining_llm_tpu.frontend.remote_replica import (
    RemoteReplica,
    ReplicaUnavailable,
)
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.frontend.wire import (
    MAX_FRAME_BYTES,
    PROTO_VERSION,
    ConnectionLost,
    ProtocolError,
    encode_frame,
    recv_frame,
    send_frame,
)
from pretraining_llm_tpu.observability.clocksync import ClockSync
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import render_merged
from pretraining_llm_tpu.resilience.faults import (
    ServingFaultInjector,
    split_serving_plan,
)

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_multihost", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _engine_kw(**kw):
    out = dict(
        max_batch=2, n_blocks=24, block_size=8, temperature=0.0,
        steps_per_sched=4, pipeline_depth=2,
    )
    out.update(kw)
    return out


def _worker_spec(**engine_kw):
    return {
        "preset": "tiny",
        "init_seed": 0,
        "model_overrides": {"compute_dtype": "float32"},
        "engine": _engine_kw(**engine_kw),
        "admission": {"max_queue_depth": 8},
    }


def _undisturbed(params, prompts, n_new, **kw):
    eng = ServingEngine(params, CFG, **_engine_kw(**kw))
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return {rids[rid]: toks for rid, toks in out.items()}


def _spawn_listen_worker(token="", engine_kw=None):
    """Spawn a pre-spawned multi-host worker (``--listen``) and return
    (proc, "host:port") once it announces its bound port."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pretraining_llm_tpu.frontend.worker",
        "--spec-json", json.dumps(_worker_spec(**(engine_kw or {}))),
        "--listen", "127.0.0.1:0",
    ]
    if token:
        cmd += ["--token", token]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=None, env=env
    )
    try:
        line = proc.stdout.readline()
        port = int(json.loads(line)["worker"]["port"])
    except Exception:
        proc.kill()
        raise
    return proc, f"127.0.0.1:{port}"


def _attach_spec(address, token="", engine_kw=None, **extra):
    spec = _worker_spec(**(engine_kw or {}))
    spec["attach"] = address
    if token:
        spec["token"] = token
    spec.update(extra)
    return spec


def _kill(procs):
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
    for proc in procs:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


# -- wire: partial writes, torn and interleaved frames (no JAX) -------------


def test_wire_send_deadline_on_stuffed_peer():
    """A peer that stops reading must not hang the sender forever: the
    chunked send loop gives up at its per-frame deadline with the
    redrivable ConnectionLost, reporting the partial write."""
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        payload = {"blob": "x" * 262144}
        with pytest.raises(ConnectionLost, match="send deadline"):
            # The peer never reads: once both kernel buffers fill, the
            # send loop can make no progress and must time out.
            for _ in range(64):
                send_frame(a, payload, deadline_s=0.5)
    finally:
        a.close()
        b.close()


def test_wire_torn_length_prefix_is_connection_lost():
    a, b = socket.socketpair()
    # Deliver 2 of the 4 length-prefix bytes, then die mid-prefix.
    a.sendall(b"\x00\x00")
    a.close()
    with pytest.raises(ConnectionLost):
        recv_frame(b)
    b.close()


def test_wire_torn_body_is_connection_lost():
    a, b = socket.socketpair()
    body = json.dumps({"op": "hello"}).encode()
    # Full prefix, half the declared body, then EOF.
    a.sendall(struct.pack(">I", len(body)) + body[: len(body) // 2])
    a.close()
    with pytest.raises(ConnectionLost):
        recv_frame(b)
    b.close()


def test_wire_interleaved_half_frames_reassemble():
    """Two frames delivered in slices that straddle the frame boundary
    (a slow peer dribbling bytes) must reassemble exactly — framing
    state never leaks across recv_frame calls."""
    a, b = socket.socketpair()
    try:
        p1 = {"op": "submit", "rid": 1, "prompt": [1, 2, 3]}
        p2 = {"op": "health", "id": 2}
        blob = encode_frame(p1) + encode_frame(p2)
        cuts = [3, len(encode_frame(p1)) - 2, len(encode_frame(p1)) + 5]
        pieces = [
            blob[i:j] for i, j in zip([0] + cuts, cuts + [len(blob)])
        ]

        def _dribble():
            for piece in pieces:
                a.sendall(piece)
                time.sleep(0.02)

        t = threading.Thread(target=_dribble, daemon=True)
        t.start()
        assert recv_frame(b) == p1
        assert recv_frame(b) == p2
        t.join(timeout=5)
    finally:
        a.close()
        b.close()


def test_wire_oversized_length_prefix_fails_fast():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">I", MAX_FRAME_BYTES + 1))
        with pytest.raises(ProtocolError):
            recv_frame(b)
        a.sendall(struct.pack(">I", 0xFFFFFFFF))
        with pytest.raises(ProtocolError):
            recv_frame(b)
    finally:
        a.close()
        b.close()


def test_wire_spans_frame_survives_dribble_and_tear():
    """The v2 batched span-export frame is an ordinary length-prefixed
    frame: sliced delivery reassembles exactly, and a peer dying mid-body
    surfaces as the redrivable ConnectionLost, same as any other op."""
    assert PROTO_VERSION >= 2  # spans frames are negotiable, not assumed
    frame = {
        "op": "spans", "g": 3, "dropped": 2,
        "spans": [
            {"name": "req.window", "t0": 1.5 + i, "dur": 0.25,
             "meta": {"trace_id": "ab" * 16, "_track": "req ab"}}
            for i in range(40)
        ],
    }
    a, b = socket.socketpair()
    try:
        blob = encode_frame(frame)
        cuts = [2, len(blob) // 3, len(blob) - 5]
        pieces = [blob[i:j] for i, j in zip([0] + cuts, cuts + [len(blob)])]

        def _dribble():
            for piece in pieces:
                a.sendall(piece)
                time.sleep(0.01)

        t = threading.Thread(target=_dribble, daemon=True)
        t.start()
        assert recv_frame(b) == frame
        t.join(timeout=5)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    a.sendall(blob[: len(blob) // 2])
    a.close()
    with pytest.raises(ConnectionLost):
        recv_frame(b)
    b.close()


# -- clock-offset estimator (no JAX, injected clocks) ------------------------


def _simulate_round_trips(sync, offset_s, rtts, stamp_fracs, t0=100.0):
    """Drive the estimator with a synthetic remote peer whose clock reads
    ``local - offset_s``: round trip i takes ``rtts[i]`` seconds and the
    peer stamps its reply at fraction ``stamp_fracs[i]`` of the trip (the
    midpoint assumption is exact at 0.5; anything else is estimator
    error the RTT/2 bound must still cover)."""
    t = t0
    for rtt, frac in zip(rtts, stamp_fracs):
        t_send = t
        t_recv = t + rtt
        t_remote = (t_send + frac * rtt) - offset_s
        sync.observe(t_send, t_recv, t_remote)
        t += rtt + 0.05


@pytest.mark.parametrize(
    "offset_s", [-5137.25, -0.5, 0.0, 0.25, 86400.0],
    ids=["far_behind", "behind", "aligned", "ahead", "far_ahead"],
)
def test_clocksync_skewed_jittery_grid(offset_s):
    """Whatever the epoch skew, the estimate lands within the advertised
    error bound, and the bound is half the best RTT seen — jittery
    (congested) round trips widen individual samples but the min-RTT
    filter keeps the headline estimate at the tightest one."""
    rng = np.random.default_rng(7)
    rtts = (0.002 + rng.random(24) * 0.040).tolist()  # 2..42 ms, jittery
    fracs = rng.random(24).tolist()  # stamp anywhere inside the trip
    sync = ClockSync(window=16)
    _simulate_round_trips(sync, offset_s, rtts, fracs)
    assert sync.n_samples == 24
    est, bound = sync.offset_s, sync.error_bound_s
    assert est is not None and bound is not None
    assert abs(est - offset_s) <= bound + 1e-9
    # The bound is half the best RTT inside the sliding window.
    assert bound == pytest.approx(min(rtts[-16:]) / 2.0)
    # to_local maps a remote stamp back to within the bound.
    t_remote = 500.0
    assert abs(sync.to_local(t_remote) - (t_remote + offset_s)) <= bound + 1e-9


def test_clocksync_tracks_drift_newest_wins_ties():
    """Equal-RTT samples tie toward the NEWEST: a drifting remote clock
    (perf_counter rates differ across hosts) keeps being re-estimated at
    every heartbeat instead of pinning the first lucky sample."""
    sync = ClockSync(window=8)
    for i in range(8):
        drift_offset = 10.0 + i * 0.001
        _simulate_round_trips(
            sync, drift_offset, [0.004], [0.5], t0=100.0 + i
        )
    assert sync.offset_s == pytest.approx(10.0 + 7 * 0.001)


def test_clocksync_window_evicts_stale_tight_sample():
    """One early lucky tight sample must not pin the estimate forever:
    once it slides out of the window, the estimate comes from the
    samples that remain."""
    sync = ClockSync(window=4)
    _simulate_round_trips(sync, 1.0, [0.001], [0.5])  # lucky + tight
    for _ in range(4):  # fills the window, evicting the tight sample
        _simulate_round_trips(sync, 2.0, [0.010], [0.5])
    assert sync.offset_s == pytest.approx(2.0)
    assert sync.error_bound_s == pytest.approx(0.005)


def test_clocksync_reset_and_bad_samples():
    with pytest.raises(ValueError, match="window"):
        ClockSync(window=0)
    sync = ClockSync()
    assert sync.offset_s is None and sync.error_bound_s is None
    assert sync.to_local(1.0) is None
    sync.observe(2.0, 1.0, 50.0)  # negative RTT: discarded
    assert sync.offset_s is None
    sync.observe(1.0, 1.01, 50.0)
    assert sync.offset_s is not None
    sync.reset()  # new connection generation: unrelated epoch
    assert sync.offset_s is None
    snap = sync.snapshot()
    # Only the accepted sample ever counted; reset keeps the tally.
    assert snap["offset_s"] is None and snap["n_samples"] == 1


# -- span ingestion: clock mapping at the router edge (no JAX) ---------------


def test_remote_span_ingest_aligns_or_flags():
    """RemoteReplica._ingest_spans maps worker-epoch timestamps through
    the live offset estimate (recording the error bound on each span) and
    flags spans that arrive before any estimate exists as ``unaligned``
    instead of plotting them at a meaningless time."""
    rec = SpanRecorder(max_events=64)
    rep = RemoteReplica(0, _worker_spec(), recorder=rec)
    tid = "ab" * 16
    # Before any clock sample: kept but flagged.
    rep._ingest_spans({
        "spans": [{"name": "req.window", "t0": 100.0, "dur": 0.1,
                   "meta": {"trace_id": tid, "_track": "req " + tid[:12]}}],
        "dropped": 3,
    })
    # After a tight round trip: mapped into the local timeline.
    rep.clock_sync.observe(10.0, 10.01, 100.0)  # offset ~= -89.995
    rep._ingest_spans({
        "spans": [{"name": "req.prefill", "t0": 100.5, "dur": 0.2,
                   "meta": {"trace_id": tid}}],
        "dropped": 0,
    })
    assert rep._c_spans.value == 2
    assert rep._c_span_drops.value == 3
    events, _ = rec.drain()
    by_name = {name: (t0, meta) for name, t0, _d, _t, _dep, meta in events}
    t0_un, meta_un = by_name["req.window"]
    assert meta_un["unaligned"] is True and meta_un["remote"] is True
    assert meta_un["worker"] == 0
    t0_al, meta_al = by_name["req.prefill"]
    assert t0_al == pytest.approx(100.5 - 89.995)
    assert meta_al["clock_err_s"] == pytest.approx(0.005)
    assert "unaligned" not in meta_al
    # Malformed entries are skipped, never crash the reader thread.
    rep._ingest_spans({"spans": [{"name": "x"}, "junk", None], "dropped": 0})
    assert rep._c_spans.value == 2


# -- fleet journal (no JAX, no socket) --------------------------------------


def test_journal_roundtrip_and_closed_append(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    j = FleetJournal(path)
    j.append({"rec": "member", "replica": 0, "mode": "attach"})
    j.append({"rec": "submit", "frid": 0, "prompt": [1, 2], "max_new": 4})
    j.close()
    j.append({"rec": "terminal", "frid": 0, "status": "done"})  # dropped
    records = FleetJournal.load(path)
    assert [r["rec"] for r in records] == ["member", "submit"]
    # Reopening appends — restart semantics, not truncation.
    j2 = FleetJournal(path)
    j2.append({"rec": "terminal", "frid": 0, "status": "done"})
    j2.close()
    assert len(FleetJournal.load(path)) == 3


def test_journal_torn_final_line_tolerated(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    j = FleetJournal(path)
    j.append({"rec": "submit", "frid": 0, "prompt": [5], "max_new": 2})
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"rec": "frontier", "frid": 0, "tok')  # crash mid-write
    records = FleetJournal.load(path)
    assert len(records) == 1 and records[0]["rec"] == "submit"
    assert FleetJournal.load(str(tmp_path / "missing.jsonl")) == []


def test_journal_recovery_plan():
    records = [
        {"rec": "member", "replica": 0, "mode": "attach"},
        {"rec": "fence", "replica": 0, "fence": 1},
        {"rec": "fence", "replica": 0, "fence": 3},
        {"rec": "fence", "replica": 1, "fence": 0},
        {"rec": "submit", "frid": 0, "prompt": [1], "max_new": 4,
         "priority": 0, "deadline_s": None},
        {"rec": "submit", "frid": 1, "prompt": [2, 3], "max_new": 6,
         "priority": 1, "deadline_s": 2.0},
        {"rec": "submit", "frid": 2, "prompt": [4], "max_new": 4,
         "priority": 0, "deadline_s": None},
        {"rec": "frontier", "frid": 1, "tokens": [9, 8, 7], "redrives": 1},
        {"rec": "terminal", "frid": 0, "status": "done"},
    ]
    plan = FleetJournal.recovery_plan(records)
    assert plan["fences"] == {0: 3, 1: 0}
    assert plan["next_frid"] == 3
    assert sorted(plan["live"]) == [1, 2]
    assert plan["live"][1]["tokens"] == [9, 8, 7]
    assert plan["live"][1]["redrives"] == 1
    assert plan["live"][1]["priority"] == 1
    assert plan["live"][2]["tokens"] == []


def test_router_recover_requires_journal_path():
    with pytest.raises(ValueError, match="journal_path"):
        Router([Replica(0, lambda: None)], recover=True)


# -- journal compaction (no JAX, no socket) ----------------------------------


def test_journal_rotation_compacts_to_recovery_plan(tmp_path):
    """Size-threshold rotation rewrites the journal down to its recovery
    fold: max fences, live submits at their frontiers (trace_id intact),
    and the frid high-water mark — and a router recovering from the
    rotated file sees EXACTLY the plan the unrotated one implied."""
    path = str(tmp_path / "fleet.jsonl")
    with pytest.raises(ValueError, match="rotate_bytes"):
        FleetJournal(path, rotate_bytes=-1)
    j = FleetJournal(path, rotate_bytes=4096)
    j.append({"rec": "member", "replica": 0, "mode": "attach"})
    j.append({"rec": "fence", "replica": 0, "fence": 2})
    j.append({"rec": "fence", "replica": 1, "fence": 5})
    filler = list(range(64))  # bulk per record so the threshold trips
    for frid in range(24):
        j.append({
            "rec": "submit", "frid": frid, "prompt": filler,
            "max_new": 4, "priority": frid % 3, "deadline_s": None,
            "trace_id": f"{frid:032x}",
        })
        if frid != 21:  # one live straggler with a frontier
            j.append({"rec": "terminal", "frid": frid, "status": "done"})
    j.append({"rec": "frontier", "frid": 21, "tokens": [9, 8], "redrives": 1})
    assert j.rotations >= 1
    assert os.path.getsize(path) < 4096
    assert not os.path.exists(path + ".rotate")  # temp swapped in, not left

    plan = FleetJournal.recovery_plan(FleetJournal.load(path))
    assert plan["fences"] == {0: 2, 1: 5}
    assert plan["next_frid"] == 24  # high-water mark survives compaction
    assert sorted(plan["live"]) == [21]
    assert plan["live"][21]["tokens"] == [9, 8]
    assert plan["live"][21]["redrives"] == 1
    assert plan["live"][21]["trace_id"] == f"{21:032x}"
    assert plan["live"][21]["prompt"] == filler

    # The journal keeps appending seamlessly after the swap.
    j.append({"rec": "terminal", "frid": 21, "status": "done"})
    j.close()
    plan2 = FleetJournal.recovery_plan(FleetJournal.load(path))
    assert plan2["live"] == {}
    assert plan2["next_frid"] == 24


def test_journal_rotation_crash_torn_mid_rotate(tmp_path):
    """Crashes around rotation never lose the journal: a failure while
    WRITING the temp aborts the rotation and keeps the original complete
    file; a stale torn ``.rotate`` temp from a crashed predecessor is
    ignored by load and overwritten by the next successful rotation."""
    path = str(tmp_path / "fleet.jsonl")
    j = FleetJournal(path, rotate_bytes=256)
    j.append({"rec": "fence", "replica": 0, "fence": 1})

    # Crash mid-temp-write: fsync blows up inside _rotate_locked's try.
    real_fsync = os.fsync

    def _boom(fd):
        raise OSError("disk full")

    os.fsync = _boom
    try:
        j.append({
            "rec": "submit", "frid": 0, "prompt": list(range(80)),
            "max_new": 4, "priority": 0, "deadline_s": None,
            "trace_id": "a" * 32,
        })
    finally:
        os.fsync = real_fsync
    assert j.rotations == 0
    assert not os.path.exists(path + ".rotate")  # aborted temp unlinked
    plan = FleetJournal.recovery_plan(FleetJournal.load(path))
    assert plan["fences"] == {0: 1} and sorted(plan["live"]) == [0]

    # A torn temp left by a crashed predecessor (died between writing
    # some of the temp and the atomic replace) must not confuse anyone.
    with open(path + ".rotate", "w", encoding="utf-8") as f:
        f.write('{"rec": "fence", "replica": 9, "fen')  # torn mid-line
    j.append({"rec": "frontier", "frid": 0, "tokens": [3], "redrives": 0})
    assert j.rotations >= 1  # this append trips a SUCCESSFUL rotation
    assert not os.path.exists(path + ".rotate")
    j.close()
    plan = FleetJournal.recovery_plan(FleetJournal.load(path))
    assert plan["fences"] == {0: 1}  # the torn temp's replica 9 is nowhere
    assert plan["live"][0]["tokens"] == [3]


# -- cross-host lineage trees: obs_report over synthetic traces (no JAX) -----


def _tev(name, ts_us, dur_us, trace_id, span_id, parent=None, **args):
    a = {"trace_id": trace_id, "span_id": span_id, **args}
    if parent is not None:
        a["parent_span_id"] = parent
    return {"ph": "X", "name": name, "ts": ts_us, "dur": dur_us,
            "pid": 1, "tid": 1, "args": a}


def _lineage_trace(clock_err_s=0.001, worker_shift_us=0.0, unaligned=False):
    """One redriven request as the merged export sees it: router root,
    two attempts (first redriven, second served by a remote worker), and
    the worker's clock-aligned subtree nested under attempt 2."""
    tid = "f" * 32
    evs = [
        _tev("req.request", 1_000_000, 200_000, tid, "r0", status="done",
             redrives=1),
        _tev("req.attempt", 1_010_000, 50_000, tid, "a1", parent="r0",
             outcome="redriven", replica=0, fence=1, redrive=0),
        _tev("req.attempt", 1_090_000, 100_000, tid, "a2", parent="r0",
             outcome="done", replica=1, fence=2, redrive=1),
        _tev("req.terminal", 1_199_000, 0, tid, "t0", parent="r0",
             status="done"),
    ]
    wargs = {"remote": True, "worker": 1}
    if unaligned:
        wargs["unaligned"] = True
    else:
        wargs["clock_err_s"] = clock_err_s
    s = worker_shift_us
    evs += [
        _tev("req.request", 1_095_000 + s, 90_000, tid, "w0", parent="a2",
             status="done", **wargs),
        _tev("req.queue", 1_096_000 + s, 2_000, tid, "w1", parent="w0",
             **wargs),
        _tev("req.prefill", 1_098_000 + s, 10_000, tid, "w2", parent="w0",
             **wargs),
        _tev("req.window", 1_110_000 + s, 60_000, tid, "w3", parent="w0",
             **wargs),
        _tev("req.first_token", 1_112_000 + s, 0, tid, "w4", parent="w0",
             **wargs),
        _tev("req.terminal", 1_184_000 + s, 0, tid, "w5", parent="w0",
             status="done", **wargs),
    ]
    return {"traceEvents": evs, "otherData": {}}


def test_check_trace_tree_accepts_worker_subtrees():
    trace = _lineage_trace()
    groups = obs_report.group_request_spans(trace)
    (tid, spans), = groups.items()
    assert obs_report.check_trace_tree(tid, spans) == []
    # The same subtree orphaned from its attempt is a structural problem.
    bad = _lineage_trace()
    for ev in bad["traceEvents"]:
        if ev["args"].get("span_id") == "w0":
            ev["args"]["parent_span_id"] = "nonexistent"
    (tid, spans), = obs_report.group_request_spans(bad).items()
    assert any("not parented to any req.attempt" in p
               for p in obs_report.check_trace_tree(tid, spans))


def test_fleet_trace_report_decomposes_across_attempts():
    report = obs_report.build_fleet_trace_report(_lineage_trace())
    assert report["problems"] == []
    assert report["n_requests"] == 1
    assert report["redriven_requests"] == 1
    assert report["n_worker_spans"] == 6
    assert report["n_unaligned"] == 0
    (req,) = report["requests"]
    seg = req["segments"]
    # placement (10ms) + attempts (150ms) + gap (30ms) + finish (10ms)
    assert seg["placement_s"] == pytest.approx(0.010)
    assert seg["attempts_s"] == pytest.approx(0.150)
    assert seg["redrive_gap_s"] == pytest.approx(0.030)
    assert seg["finish_s"] == pytest.approx(0.010)
    assert abs(req["sum_error_s"]) < 1e-9  # sums to e2e by construction
    a1, a2 = req["attempts"]
    assert (a1["outcome"], a1["replica"], a1["redrive"]) == ("redriven", 0, 0)
    assert (a2["outcome"], a2["replica"], a2["redrive"]) == ("done", 1, 1)
    assert a2["worker_spans"] == 6
    assert a2["worker_decode_s"] == pytest.approx(0.060)
    assert a2["clock_err_s"] == pytest.approx(0.001)
    # The gap joins to the redrive event that explains it.
    events = [{"event": "redrive", "trace_id": "f" * 32, "reason": "crash",
               "t_wall": 1.07}]
    report = obs_report.build_fleet_trace_report(_lineage_trace(), events)
    (req,) = report["requests"]
    assert req["gaps"][0]["causes"] == ["redrive:crash"]


def test_fleet_trace_report_strict_problems():
    # Unalignable spans (no offset estimate at ingest) are strict.
    report = obs_report.build_fleet_trace_report(_lineage_trace(unaligned=True))
    assert report["n_unaligned"] == 6
    assert any("unalignable" in p for p in report["problems"])
    # A worker span outside its attempt window beyond the recorded clock
    # error bound means the alignment claim is false — also strict.
    report = obs_report.build_fleet_trace_report(
        _lineage_trace(clock_err_s=0.001, worker_shift_us=20_000)
    )
    assert any("outside its attempt window" in p for p in report["problems"])
    # Within the bound (+ slack): fine.
    report = obs_report.build_fleet_trace_report(
        _lineage_trace(clock_err_s=0.025, worker_shift_us=20_000)
    )
    assert not any("outside" in p for p in report["problems"])


# -- fault grammar + actions + config ---------------------------------------


def test_partition_faults_are_process_kinds():
    engine, process = split_serving_plan(
        "partition@req2:r0, wire_delay@req1:r1, replica_crash@req3:r0"
    )
    assert engine == "replica_crash@req3:r0"
    assert process == "partition@req2:r0,wire_delay@req1:r1"


def test_fleet_action_partition_heal_validation():
    assert FleetAction(at_s=0.5, kind="partition", replica=0).kind == "partition"
    assert FleetAction(at_s=1.0, kind="heal", replica=1).kind == "heal"
    with pytest.raises(ValueError):
        FleetAction(at_s=0.5, kind="partition", replica=0, update={"x": 1})


def test_frontend_config_multihost_validation():
    ok = FrontendConfig(
        replicas=2, replica_mode="process",
        worker_attach="10.0.0.1:7000,10.0.0.2:7000",
        attach_token="s3cret", lease_s=2.0, journal_path="fleet.jsonl",
    )
    assert ok.lease_s == 2.0
    with pytest.raises(ValueError, match="lease_s"):
        FrontendConfig(lease_s=-1.0)
    with pytest.raises(ValueError, match="replica_mode"):
        FrontendConfig(replicas=1, worker_attach="h:1")
    with pytest.raises(ValueError, match="addresses"):
        FrontendConfig(
            replicas=2, replica_mode="process", worker_attach="h:1"
        )
    with pytest.raises(ValueError, match="host:port"):
        FrontendConfig(
            replicas=1, replica_mode="process", worker_attach="nonsense"
        )
    with pytest.raises(ValueError, match="attach_token"):
        FrontendConfig(attach_token="s3cret")


# -- attach handshake: token, fingerprint, detach-survival ------------------


@pytest.mark.slow
def test_attach_handshake_token_fingerprint_and_detach(params):
    """One pre-spawned ``--listen`` worker: a wrong token is refused, a
    wrong expected fingerprint is refused, the right token serves decode
    bit-identically, and a router detach leaves the worker alive and
    ready for the NEXT attach."""
    prompts = _prompts(2)
    ref = _undisturbed(params, prompts, 4)
    proc, addr = _spawn_listen_worker(token="s3cret")
    try:
        # Anyone can reach the TCP port; only the token holder attaches.
        bad = RemoteReplica(0, _attach_spec(addr, token="wrong"))
        with pytest.raises(Exception, match="unauthorized|token"):
            bad.start()

        # Wrong weights behind the address: the fingerprint check in the
        # hello refuses the attach before any traffic is routed.
        finger = RemoteReplica(
            0, _attach_spec(addr, token="s3cret", expect_fingerprint="bogus")
        )
        with pytest.raises(ReplicaUnavailable, match="fingerprint"):
            finger.start()

        rep = RemoteReplica(0, _attach_spec(addr, token="s3cret"))
        rep.start()
        assert rep.mode == "attach"
        assert rep.proc is None  # not our child — attached, not spawned
        reqs = [rep.submit(p, 4) for p in prompts]
        for i, r in enumerate(reqs):
            status, tokens, _ = r.result(timeout=120)
            assert status == "done"
            assert tokens == ref[i]
        rep.stop()
        assert proc.poll() is None, "detach must NOT kill the worker"

        # The parked worker serves the next attach (fresh router).
        rep2 = RemoteReplica(0, _attach_spec(addr, token="s3cret"))
        rep2.start()
        w = rep2.submit(prompts[0], 4)
        status, tokens, _ = w.result(timeout=120)
        assert status == "done" and tokens == ref[0]
        rep2.stop()
        assert proc.poll() is None
    finally:
        _kill([proc])


# -- partition drill: lease expiry, fence drop, bit-identity ----------------


@pytest.mark.slow
def test_partition_heal_fence_bit_identity(params, tmp_path):
    """Blackhole an attached worker mid-decode. The lease detects it
    (no RST ever arrives), its in-flight requests redrive to the
    survivor bit-identically, and after heal the frames it streamed
    into the void arrive stamped with the stale fence generation — every
    one counted and dropped, zero duplicate tokens delivered."""
    prompts = _prompts(4)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new)
    path = tmp_path / "events.jsonl"
    bus = EventBus(jsonl_path=str(path))
    procs, addrs = [], []
    for _ in range(2):
        proc, addr = _spawn_listen_worker()
        procs.append(proc)
        addrs.append(addr)
    try:
        faults = ServingFaultInjector("partition@req2:r0", bus=bus)
        reps = [
            RemoteReplica(
                i, _attach_spec(addrs[i]), bus=bus,
                fault_injector=faults, lease_s=0.8,
            )
            for i in range(2)
        ]
        # Backoff > test body: no relaunch tears down the partitioned
        # gate, so the post-heal backlog survives to hit the fence.
        router = Router(reps, bus=bus, eject_backoff_s=60.0)
        with router:
            reqs = [router.submit(p, n_new) for p in prompts]
            results = [r.result(timeout=120) for r in reqs]
            for i, (status, tokens, info) in enumerate(results):
                assert status == "done", (i, status, info)
                assert tokens == ref[i], f"request {i} diverged"
            assert router.counters["redrives"] >= 1
            assert router.counters["ejects"] >= 1
            assert reps[0]._c_lease.value >= 1
            assert reps[0].fence >= 1  # ejected -> fenced
            # Heal: the blackholed worker's buffered frames flood in,
            # all stamped with the pre-bump generation.
            reps[0].heal()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if reps[0]._c_fenced.value >= 1:
                    break
                time.sleep(0.05)
            assert reps[0]._c_fenced.value >= 1, (
                "healed backlog never hit the fence filter"
            )
            # Zero duplicates: the fence dropped the stale stream, so no
            # request's committed tokens overran its budget.
            for _, tokens, _info in results:
                assert len(tokens) == n_new
            text = render_merged([rep.registry for rep in reps])
            assert lint_exposition(text) == []
            assert "pllm_serving_lease_expiries_total" in text
            assert "pllm_serving_fenced_frames_total" in text
    finally:
        _kill(procs)
    bus.close()

    events = [json.loads(ln) for ln in path.read_text().splitlines()]
    report = obs_report.build_fleet_report(events)
    assert report["lost_requests"] == 0
    pt = report["partitions"]
    assert pt is not None and pt["injected"] == 1
    assert pt["healed"] == 1
    inc = pt["incidents"][0]
    assert inc["replica"] == 0
    assert inc["detected_by"] == "lease_expiry"
    assert inc["redrives_caused"] >= 1
    assert not any("UNDETECTED" in p for p in report["problems"])


# -- router crash + journal recovery ----------------------------------------

_RESTART_GRID = [
    (1, False), (1, True), (2, False), (2, True), (3, False), (3, True),
]


@pytest.mark.slow
@pytest.mark.parametrize("depth,cache", _RESTART_GRID)
def test_router_restart_recovers_journal(params, tmp_path, depth, cache):
    """Kill the router mid-burst (no shutdown, no terminals) with
    attached workers alive. A new router recovering from the journal
    re-attaches the same workers, fences the old generation, and
    finishes every in-flight request exactly once — greedy outputs
    bit-identical to an undisturbed run, at every pipeline depth,
    prefix cache on or off."""
    prompts = _prompts(4)
    n_new = 6
    kw = dict(pipeline_depth=depth, prefix_cache=cache)
    ref = _undisturbed(params, prompts, n_new, **kw)
    journal = str(tmp_path / "fleet.jsonl")
    token = "journal-tok"
    procs, addrs = [], []
    for _ in range(2):
        proc, addr = _spawn_listen_worker(token=token, engine_kw=kw)
        procs.append(proc)
        addrs.append(addr)
    try:
        reps1 = [
            RemoteReplica(
                i, _attach_spec(addrs[i], token=token, engine_kw=kw),
                lease_s=1.0,
            )
            for i in range(2)
        ]
        router1 = Router(reps1, eject_backoff_s=60.0, journal_path=journal)
        router1.start()
        reqs = [router1.submit(p, n_new) for p in prompts]
        time.sleep(0.15)  # mid-burst: some done, some in flight
        router1.abort()  # the crash: no RPCs, no terminals, no events
        fence_before = {rep.index: rep.fence for rep in reps1}

        finished = {
            i: list(r.tokens)
            for i, r in enumerate(reqs) if r.status == "done"
        }
        for i, tokens in finished.items():
            assert tokens == ref[i]
        pending = set(range(len(prompts))) - set(finished)
        assert all(p.poll() is None for p in procs), (
            "workers must survive the router crash"
        )

        reps2 = [
            RemoteReplica(
                i, _attach_spec(addrs[i], token=token, engine_kw=kw),
                lease_s=1.0,
            )
            for i in range(2)
        ]
        router2 = Router(
            reps2, eject_backoff_s=60.0,
            journal_path=journal, recover=True,
        )
        try:
            router2.start()
            # The old generation is fenced everywhere before traffic.
            for rep in reps2:
                assert rep.fence > fence_before[rep.index]
            # Exactly once: precisely the requests without journaled
            # terminals are replayed — finished ones never re-run.
            assert set(router2.recovered) == pending
            assert router2.counters["journal_replays"] == len(pending)
            for frid, rreq in router2.recovered.items():
                status, tokens, info = rreq.result(timeout=120)
                assert status == "done", (frid, status, info)
                assert tokens == ref[frid], (
                    f"replayed request {frid} diverged after recovery"
                )
        finally:
            router2.stop()
        assert all(p.poll() is None for p in procs)
    finally:
        _kill(procs)
