"""Multi-host fleet: remote worker attach, lease-fenced partition
tolerance, and the crash-recoverable router control plane.

The correctness bar is test_process_fleet.py's, extended to faults a
real network brings that PR 12's connection-loss model cannot express:

- a silent PARTITION (no RST, no EOF — reads hang, writes buffer) must
  be detected by the heartbeat lease, its requests redriven to a
  survivor bit-identically, and the frames the blackholed worker
  streamed into the void must arrive after heal stamped with a stale
  fence generation — counted and DROPPED, never delivered twice;
- a pre-spawned ``worker.py --listen`` worker must refuse attaches
  with a bad token or the wrong engine fingerprint, survive a router
  detach, and serve the next attach;
- a router CRASH (no shutdown, no terminals — just gone) must be
  recoverable from the write-ahead fleet journal: a new router
  re-attaches the still-live workers, fences the old generation, and
  finishes every journaled in-flight request exactly once with greedy
  output bit-identical to an undisturbed run, at every pipeline depth,
  prefix cache on or off.

Workers build their own params from (preset, init_seed) — the same
``init_params(cfg, key(0))`` this module's reference engine uses — so
bit-identity assertions compare real decode output across processes.

The wire/journal/config unit tests are tier-1 (no JAX, no subprocess);
the attach/partition/restart drills spawn real worker processes and
build engines, so they are marked ``slow`` and run in ``ci_smoke.sh``.
"""

import dataclasses
import importlib.util
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.journal import FleetJournal
from pretraining_llm_tpu.frontend.loadgen import FleetAction
from pretraining_llm_tpu.frontend.remote_replica import (
    RemoteReplica,
    ReplicaUnavailable,
)
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.frontend.wire import (
    MAX_FRAME_BYTES,
    ConnectionLost,
    ProtocolError,
    encode_frame,
    recv_frame,
    send_frame,
)
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import render_merged
from pretraining_llm_tpu.resilience.faults import (
    ServingFaultInjector,
    split_serving_plan,
)

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_multihost", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _engine_kw(**kw):
    out = dict(
        max_batch=2, n_blocks=24, block_size=8, temperature=0.0,
        steps_per_sched=4, pipeline_depth=2,
    )
    out.update(kw)
    return out


def _worker_spec(**engine_kw):
    return {
        "preset": "tiny",
        "init_seed": 0,
        "model_overrides": {"compute_dtype": "float32"},
        "engine": _engine_kw(**engine_kw),
        "admission": {"max_queue_depth": 8},
    }


def _undisturbed(params, prompts, n_new, **kw):
    eng = ServingEngine(params, CFG, **_engine_kw(**kw))
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return {rids[rid]: toks for rid, toks in out.items()}


def _spawn_listen_worker(token="", engine_kw=None):
    """Spawn a pre-spawned multi-host worker (``--listen``) and return
    (proc, "host:port") once it announces its bound port."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [
        sys.executable, "-m", "pretraining_llm_tpu.frontend.worker",
        "--spec-json", json.dumps(_worker_spec(**(engine_kw or {}))),
        "--listen", "127.0.0.1:0",
    ]
    if token:
        cmd += ["--token", token]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=None, env=env
    )
    try:
        line = proc.stdout.readline()
        port = int(json.loads(line)["worker"]["port"])
    except Exception:
        proc.kill()
        raise
    return proc, f"127.0.0.1:{port}"


def _attach_spec(address, token="", engine_kw=None, **extra):
    spec = _worker_spec(**(engine_kw or {}))
    spec["attach"] = address
    if token:
        spec["token"] = token
    spec.update(extra)
    return spec


def _kill(procs):
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
    for proc in procs:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


# -- wire: partial writes, torn and interleaved frames (no JAX) -------------


def test_wire_send_deadline_on_stuffed_peer():
    """A peer that stops reading must not hang the sender forever: the
    chunked send loop gives up at its per-frame deadline with the
    redrivable ConnectionLost, reporting the partial write."""
    a, b = socket.socketpair()
    try:
        a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
        b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
        payload = {"blob": "x" * 262144}
        with pytest.raises(ConnectionLost, match="send deadline"):
            # The peer never reads: once both kernel buffers fill, the
            # send loop can make no progress and must time out.
            for _ in range(64):
                send_frame(a, payload, deadline_s=0.5)
    finally:
        a.close()
        b.close()


def test_wire_torn_length_prefix_is_connection_lost():
    a, b = socket.socketpair()
    # Deliver 2 of the 4 length-prefix bytes, then die mid-prefix.
    a.sendall(b"\x00\x00")
    a.close()
    with pytest.raises(ConnectionLost):
        recv_frame(b)
    b.close()


def test_wire_torn_body_is_connection_lost():
    a, b = socket.socketpair()
    body = json.dumps({"op": "hello"}).encode()
    # Full prefix, half the declared body, then EOF.
    a.sendall(struct.pack(">I", len(body)) + body[: len(body) // 2])
    a.close()
    with pytest.raises(ConnectionLost):
        recv_frame(b)
    b.close()


def test_wire_interleaved_half_frames_reassemble():
    """Two frames delivered in slices that straddle the frame boundary
    (a slow peer dribbling bytes) must reassemble exactly — framing
    state never leaks across recv_frame calls."""
    a, b = socket.socketpair()
    try:
        p1 = {"op": "submit", "rid": 1, "prompt": [1, 2, 3]}
        p2 = {"op": "health", "id": 2}
        blob = encode_frame(p1) + encode_frame(p2)
        cuts = [3, len(encode_frame(p1)) - 2, len(encode_frame(p1)) + 5]
        pieces = [
            blob[i:j] for i, j in zip([0] + cuts, cuts + [len(blob)])
        ]

        def _dribble():
            for piece in pieces:
                a.sendall(piece)
                time.sleep(0.02)

        t = threading.Thread(target=_dribble, daemon=True)
        t.start()
        assert recv_frame(b) == p1
        assert recv_frame(b) == p2
        t.join(timeout=5)
    finally:
        a.close()
        b.close()


def test_wire_oversized_length_prefix_fails_fast():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">I", MAX_FRAME_BYTES + 1))
        with pytest.raises(ProtocolError):
            recv_frame(b)
        a.sendall(struct.pack(">I", 0xFFFFFFFF))
        with pytest.raises(ProtocolError):
            recv_frame(b)
    finally:
        a.close()
        b.close()


# -- fleet journal (no JAX, no socket) --------------------------------------


def test_journal_roundtrip_and_closed_append(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    j = FleetJournal(path)
    j.append({"rec": "member", "replica": 0, "mode": "attach"})
    j.append({"rec": "submit", "frid": 0, "prompt": [1, 2], "max_new": 4})
    j.close()
    j.append({"rec": "terminal", "frid": 0, "status": "done"})  # dropped
    records = FleetJournal.load(path)
    assert [r["rec"] for r in records] == ["member", "submit"]
    # Reopening appends — restart semantics, not truncation.
    j2 = FleetJournal(path)
    j2.append({"rec": "terminal", "frid": 0, "status": "done"})
    j2.close()
    assert len(FleetJournal.load(path)) == 3


def test_journal_torn_final_line_tolerated(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    j = FleetJournal(path)
    j.append({"rec": "submit", "frid": 0, "prompt": [5], "max_new": 2})
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"rec": "frontier", "frid": 0, "tok')  # crash mid-write
    records = FleetJournal.load(path)
    assert len(records) == 1 and records[0]["rec"] == "submit"
    assert FleetJournal.load(str(tmp_path / "missing.jsonl")) == []


def test_journal_recovery_plan():
    records = [
        {"rec": "member", "replica": 0, "mode": "attach"},
        {"rec": "fence", "replica": 0, "fence": 1},
        {"rec": "fence", "replica": 0, "fence": 3},
        {"rec": "fence", "replica": 1, "fence": 0},
        {"rec": "submit", "frid": 0, "prompt": [1], "max_new": 4,
         "priority": 0, "deadline_s": None},
        {"rec": "submit", "frid": 1, "prompt": [2, 3], "max_new": 6,
         "priority": 1, "deadline_s": 2.0},
        {"rec": "submit", "frid": 2, "prompt": [4], "max_new": 4,
         "priority": 0, "deadline_s": None},
        {"rec": "frontier", "frid": 1, "tokens": [9, 8, 7], "redrives": 1},
        {"rec": "terminal", "frid": 0, "status": "done"},
    ]
    plan = FleetJournal.recovery_plan(records)
    assert plan["fences"] == {0: 3, 1: 0}
    assert plan["next_frid"] == 3
    assert sorted(plan["live"]) == [1, 2]
    assert plan["live"][1]["tokens"] == [9, 8, 7]
    assert plan["live"][1]["redrives"] == 1
    assert plan["live"][1]["priority"] == 1
    assert plan["live"][2]["tokens"] == []


def test_router_recover_requires_journal_path():
    with pytest.raises(ValueError, match="journal_path"):
        Router([Replica(0, lambda: None)], recover=True)


# -- fault grammar + actions + config ---------------------------------------


def test_partition_faults_are_process_kinds():
    engine, process = split_serving_plan(
        "partition@req2:r0, wire_delay@req1:r1, replica_crash@req3:r0"
    )
    assert engine == "replica_crash@req3:r0"
    assert process == "partition@req2:r0,wire_delay@req1:r1"


def test_fleet_action_partition_heal_validation():
    assert FleetAction(at_s=0.5, kind="partition", replica=0).kind == "partition"
    assert FleetAction(at_s=1.0, kind="heal", replica=1).kind == "heal"
    with pytest.raises(ValueError):
        FleetAction(at_s=0.5, kind="partition", replica=0, update={"x": 1})


def test_frontend_config_multihost_validation():
    ok = FrontendConfig(
        replicas=2, replica_mode="process",
        worker_attach="10.0.0.1:7000,10.0.0.2:7000",
        attach_token="s3cret", lease_s=2.0, journal_path="fleet.jsonl",
    )
    assert ok.lease_s == 2.0
    with pytest.raises(ValueError, match="lease_s"):
        FrontendConfig(lease_s=-1.0)
    with pytest.raises(ValueError, match="replica_mode"):
        FrontendConfig(replicas=1, worker_attach="h:1")
    with pytest.raises(ValueError, match="addresses"):
        FrontendConfig(
            replicas=2, replica_mode="process", worker_attach="h:1"
        )
    with pytest.raises(ValueError, match="host:port"):
        FrontendConfig(
            replicas=1, replica_mode="process", worker_attach="nonsense"
        )
    with pytest.raises(ValueError, match="attach_token"):
        FrontendConfig(attach_token="s3cret")


# -- attach handshake: token, fingerprint, detach-survival ------------------


@pytest.mark.slow
def test_attach_handshake_token_fingerprint_and_detach(params):
    """One pre-spawned ``--listen`` worker: a wrong token is refused, a
    wrong expected fingerprint is refused, the right token serves decode
    bit-identically, and a router detach leaves the worker alive and
    ready for the NEXT attach."""
    prompts = _prompts(2)
    ref = _undisturbed(params, prompts, 4)
    proc, addr = _spawn_listen_worker(token="s3cret")
    try:
        # Anyone can reach the TCP port; only the token holder attaches.
        bad = RemoteReplica(0, _attach_spec(addr, token="wrong"))
        with pytest.raises(Exception, match="unauthorized|token"):
            bad.start()

        # Wrong weights behind the address: the fingerprint check in the
        # hello refuses the attach before any traffic is routed.
        finger = RemoteReplica(
            0, _attach_spec(addr, token="s3cret", expect_fingerprint="bogus")
        )
        with pytest.raises(ReplicaUnavailable, match="fingerprint"):
            finger.start()

        rep = RemoteReplica(0, _attach_spec(addr, token="s3cret"))
        rep.start()
        assert rep.mode == "attach"
        assert rep.proc is None  # not our child — attached, not spawned
        reqs = [rep.submit(p, 4) for p in prompts]
        for i, r in enumerate(reqs):
            status, tokens, _ = r.result(timeout=120)
            assert status == "done"
            assert tokens == ref[i]
        rep.stop()
        assert proc.poll() is None, "detach must NOT kill the worker"

        # The parked worker serves the next attach (fresh router).
        rep2 = RemoteReplica(0, _attach_spec(addr, token="s3cret"))
        rep2.start()
        w = rep2.submit(prompts[0], 4)
        status, tokens, _ = w.result(timeout=120)
        assert status == "done" and tokens == ref[0]
        rep2.stop()
        assert proc.poll() is None
    finally:
        _kill([proc])


# -- partition drill: lease expiry, fence drop, bit-identity ----------------


@pytest.mark.slow
def test_partition_heal_fence_bit_identity(params, tmp_path):
    """Blackhole an attached worker mid-decode. The lease detects it
    (no RST ever arrives), its in-flight requests redrive to the
    survivor bit-identically, and after heal the frames it streamed
    into the void arrive stamped with the stale fence generation — every
    one counted and dropped, zero duplicate tokens delivered."""
    prompts = _prompts(4)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new)
    path = tmp_path / "events.jsonl"
    bus = EventBus(jsonl_path=str(path))
    procs, addrs = [], []
    for _ in range(2):
        proc, addr = _spawn_listen_worker()
        procs.append(proc)
        addrs.append(addr)
    try:
        faults = ServingFaultInjector("partition@req2:r0", bus=bus)
        reps = [
            RemoteReplica(
                i, _attach_spec(addrs[i]), bus=bus,
                fault_injector=faults, lease_s=0.8,
            )
            for i in range(2)
        ]
        # Backoff > test body: no relaunch tears down the partitioned
        # gate, so the post-heal backlog survives to hit the fence.
        router = Router(reps, bus=bus, eject_backoff_s=60.0)
        with router:
            reqs = [router.submit(p, n_new) for p in prompts]
            results = [r.result(timeout=120) for r in reqs]
            for i, (status, tokens, info) in enumerate(results):
                assert status == "done", (i, status, info)
                assert tokens == ref[i], f"request {i} diverged"
            assert router.counters["redrives"] >= 1
            assert router.counters["ejects"] >= 1
            assert reps[0]._c_lease.value >= 1
            assert reps[0].fence >= 1  # ejected -> fenced
            # Heal: the blackholed worker's buffered frames flood in,
            # all stamped with the pre-bump generation.
            reps[0].heal()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if reps[0]._c_fenced.value >= 1:
                    break
                time.sleep(0.05)
            assert reps[0]._c_fenced.value >= 1, (
                "healed backlog never hit the fence filter"
            )
            # Zero duplicates: the fence dropped the stale stream, so no
            # request's committed tokens overran its budget.
            for _, tokens, _info in results:
                assert len(tokens) == n_new
            text = render_merged([rep.registry for rep in reps])
            assert lint_exposition(text) == []
            assert "pllm_serving_lease_expiries_total" in text
            assert "pllm_serving_fenced_frames_total" in text
    finally:
        _kill(procs)
    bus.close()

    events = [json.loads(ln) for ln in path.read_text().splitlines()]
    report = obs_report.build_fleet_report(events)
    assert report["lost_requests"] == 0
    pt = report["partitions"]
    assert pt is not None and pt["injected"] == 1
    assert pt["healed"] == 1
    inc = pt["incidents"][0]
    assert inc["replica"] == 0
    assert inc["detected_by"] == "lease_expiry"
    assert inc["redrives_caused"] >= 1
    assert not any("UNDETECTED" in p for p in report["problems"])


# -- router crash + journal recovery ----------------------------------------

_RESTART_GRID = [
    (1, False), (1, True), (2, False), (2, True), (3, False), (3, True),
]


@pytest.mark.slow
@pytest.mark.parametrize("depth,cache", _RESTART_GRID)
def test_router_restart_recovers_journal(params, tmp_path, depth, cache):
    """Kill the router mid-burst (no shutdown, no terminals) with
    attached workers alive. A new router recovering from the journal
    re-attaches the same workers, fences the old generation, and
    finishes every in-flight request exactly once — greedy outputs
    bit-identical to an undisturbed run, at every pipeline depth,
    prefix cache on or off."""
    prompts = _prompts(4)
    n_new = 6
    kw = dict(pipeline_depth=depth, prefix_cache=cache)
    ref = _undisturbed(params, prompts, n_new, **kw)
    journal = str(tmp_path / "fleet.jsonl")
    token = "journal-tok"
    procs, addrs = [], []
    for _ in range(2):
        proc, addr = _spawn_listen_worker(token=token, engine_kw=kw)
        procs.append(proc)
        addrs.append(addr)
    try:
        reps1 = [
            RemoteReplica(
                i, _attach_spec(addrs[i], token=token, engine_kw=kw),
                lease_s=1.0,
            )
            for i in range(2)
        ]
        router1 = Router(reps1, eject_backoff_s=60.0, journal_path=journal)
        router1.start()
        reqs = [router1.submit(p, n_new) for p in prompts]
        time.sleep(0.15)  # mid-burst: some done, some in flight
        router1.abort()  # the crash: no RPCs, no terminals, no events
        fence_before = {rep.index: rep.fence for rep in reps1}

        finished = {
            i: list(r.tokens)
            for i, r in enumerate(reqs) if r.status == "done"
        }
        for i, tokens in finished.items():
            assert tokens == ref[i]
        pending = set(range(len(prompts))) - set(finished)
        assert all(p.poll() is None for p in procs), (
            "workers must survive the router crash"
        )

        reps2 = [
            RemoteReplica(
                i, _attach_spec(addrs[i], token=token, engine_kw=kw),
                lease_s=1.0,
            )
            for i in range(2)
        ]
        router2 = Router(
            reps2, eject_backoff_s=60.0,
            journal_path=journal, recover=True,
        )
        try:
            router2.start()
            # The old generation is fenced everywhere before traffic.
            for rep in reps2:
                assert rep.fence > fence_before[rep.index]
            # Exactly once: precisely the requests without journaled
            # terminals are replayed — finished ones never re-run.
            assert set(router2.recovered) == pending
            assert router2.counters["journal_replays"] == len(pending)
            for frid, rreq in router2.recovered.items():
                status, tokens, info = rreq.result(timeout=120)
                assert status == "done", (frid, status, info)
                assert tokens == ref[frid], (
                    f"replayed request {frid} diverged after recovery"
                )
        finally:
            router2.stop()
        assert all(p.poll() is None for p in procs)
    finally:
        _kill(procs)
