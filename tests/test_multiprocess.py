"""Real 2-process jax.distributed tests (CPU backend, localhost coordinator).

The multi-host path the reference shipped but never ran (SURVEY §A: its DDP
bootstrap crashes on a missing config key). Here two actual OS processes form
a jax.distributed cluster, train with a cross-process mesh, checkpoint from
all processes (internal barriers — the round-1 host-0-gated save would
deadlock exactly here), die, resume, and must reproduce the uninterrupted
run's loss bit-exactly.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import pytest

# jaxlib < 0.5 has no cross-process collectives on the CPU backend: the
# workers die in broadcast_one_to_all with "Multiprocess computations aren't
# implemented on the CPU backend", so the 2-process drills can't run at all.
pytestmark = pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="CPU multiprocess collectives need jaxlib >= 0.5",
)

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_mp_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_pair(mode: str, workdir: str) -> None:
    env = dict(os.environ)
    # The outer test env forces 8 virtual devices; workers set their own 2.
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    procs = []
    for pid in (0, 1):
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    WORKER,
                    "--mode",
                    mode,
                    "--port",
                    str(port),
                    "--process-id",
                    str(pid),
                    "--workdir",
                    workdir,
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{mode}: worker hung (multi-host deadlock?)")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{mode} worker {pid} failed:\n{out[-3000:]}"


def _result(workdir: str, mode: str, pid: int) -> dict:
    with open(os.path.join(workdir, f"result.{mode}.p{pid}.json")) as f:
        return json.load(f)


def test_two_process_checkpoint_kill_resume(tmp_path):
    straight_dir = str(tmp_path / "straight")
    resumed_dir = str(tmp_path / "resumed")
    os.makedirs(straight_dir)
    os.makedirs(resumed_dir)

    _run_pair("straight", straight_dir)
    _run_pair("part1", resumed_dir)

    # The "kill": part1 exited after its step-3 checkpoint. Both processes
    # must have written their own data-RNG sidecar (host-0-only state was the
    # round-1 resume-correctness bug).
    ckpt = os.path.join(resumed_dir, "ckpt", "step-3")
    assert os.path.isdir(ckpt), "periodic checkpoint missing after part1"
    for pid in (0, 1):
        assert os.path.exists(os.path.join(ckpt, f"local.p{pid}.json"))

    _run_pair("part2", resumed_dir)

    straight = _result(straight_dir, "straight", 0)
    resumed = _result(resumed_dir, "part2", 0)
    assert resumed["start_step"] == 3
    # Loss is a global-batch scalar: identical on both processes...
    assert _result(straight_dir, "straight", 1)["loss"] == straight["loss"]
    assert _result(resumed_dir, "part2", 1)["loss"] == resumed["loss"]
    # ...and the interrupted+resumed run reproduces the uninterrupted run
    # bit-exactly (params + optimizer moments + per-process data RNG all
    # round-tripped through the checkpoint).
    assert resumed["loss"] == straight["loss"]


def test_two_process_asymmetric_preemption(tmp_path):
    """SIGTERM lands on ONE process only; the stop flag syncs at a log
    boundary so both enter the collective checkpoint save together, stop at
    the SAME step, and exit cleanly (no deadlock, no divergent saves)."""
    workdir = str(tmp_path / "preempt")
    os.makedirs(workdir)
    _run_pair("preempt", workdir)

    r0 = _result(workdir, "preempt", 0)
    r1 = _result(workdir, "preempt", 1)
    # Per-process save records: both processes checkpointed exactly once, at
    # the SAME early step (a divergent stop would show different steps here
    # even though they share the checkpoint directory).
    assert r0["saved_steps"] == r1["saved_steps"], (r0, r1)
    assert len(r0["saved_steps"]) == 1 and r0["saved_steps"][0] < 20, r0
    step = r0["saved_steps"][0]
    # Both processes wrote their shards + data-RNG sidecars at the stop step.
    for pid in (0, 1):
        assert os.path.exists(
            os.path.join(workdir, "ckpt", f"step-{step}", f"local.p{pid}.json")
        )
