"""Native C++ batch gatherer: build, correctness vs source stream, determinism."""

import numpy as np
import pytest

from pretraining_llm_tpu.data.native_batcher import NativeBatchIterator, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain to build the native batcher"
)


@pytest.fixture()
def token_file(tmp_path):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 60000, size=50_000, dtype=np.uint16)
    path = tmp_path / "train.bin"
    tokens.tofile(path)
    return str(path), tokens


def test_batches_are_verbatim_windows(token_file):
    path, tokens = token_file
    it = NativeBatchIterator(path, batch_size=8, context_length=32, seed=1)
    x, y = next(it)
    assert x.shape == (8, 32) and x.dtype == np.int32
    flat = tokens.astype(np.int32)
    for xr, yr in zip(x, y):
        # x must be a verbatim window, y its shift-by-one
        matches = np.where(flat[: len(flat) - 33] == xr[0])[0]
        assert any(
            np.array_equal(flat[m : m + 32], xr) and np.array_equal(flat[m + 1 : m + 33], yr)
            for m in matches
        )


def test_counter_determinism_and_state_roundtrip(token_file):
    path, _ = token_file
    a = NativeBatchIterator(path, 4, 16, seed=7)
    b = NativeBatchIterator(path, 4, 16, seed=7)
    for _ in range(3):
        xa, _ = next(a)
        xb, _ = next(b)
        np.testing.assert_array_equal(xa, xb)
    # State is just the counter: replay from saved state matches.
    saved = a.state()
    x1, _ = next(a)
    c = NativeBatchIterator(path, 4, 16, seed=7)
    c.set_state(saved)
    x2, _ = next(c)
    np.testing.assert_array_equal(x1, x2)
    # Different seed differs.
    d = NativeBatchIterator(path, 4, 16, seed=8)
    assert not np.array_equal(next(d)[0], x2)


def test_sharding_contiguous(token_file):
    path, tokens = token_file
    it1 = NativeBatchIterator(path, 8, 16, seed=0, shard_index=1, shard_count=2)
    x1, _ = next(it1)
    src1 = tokens[len(tokens) // 2 :].astype(np.int32)
    for row in x1:
        matches = np.where(src1[: len(src1) - 16] == row[0])[0]
        assert any(np.array_equal(src1[m : m + 16], row) for m in matches)


def test_multithreaded_matches_single_thread(token_file):
    path, _ = token_file
    a = NativeBatchIterator(path, 32, 64, seed=3, n_threads=1)
    b = NativeBatchIterator(path, 32, 64, seed=3, n_threads=8)
    for _ in range(3):
        xa, ya = next(a)
        xb, yb = next(b)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)


def test_too_small_file_rejected(tmp_path):
    path = tmp_path / "tiny.bin"
    np.arange(8, dtype=np.uint16).tofile(path)
    with pytest.raises(ValueError):
        NativeBatchIterator(str(path), 1, 64)


def test_trainer_uses_native_when_available(tmp_path, token_file):
    path, _ = token_file
    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.data.native_batcher import NativeBatchIterator as NBI
    from pretraining_llm_tpu.training.trainer import Trainer

    cfg = get_preset("tiny").with_overrides(
        {
            "model.vocab_size": 60000,
            "data.train_path": path,
            "data.val_path": path,
            "train.train_steps": 2,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
            "train.log_interval": 100,
            "train.checkpoint_dir": str(tmp_path / "ck"),
        }
    )
    t = Trainer(cfg, resume=False)
    assert isinstance(t.train_iterator, NBI)
    t.train()
