"""Observability subsystem: event bus, spans, goodput, exporters, analyzer.

Covers the whole telemetry chain: events stamp/serialize correctly (incl.
the NaN-corruption regression in the metrics JSONL), spans export valid
Chrome trace JSON, the goodput fold decomposes synthetic event streams
(rollback replay excluded from productive time), the Prometheus textfile is
well-formed, the offline analyzer gates unparseable lines, and a real tiny
Trainer run emits a coherent stream — with no device→host syncs between log
boundaries on the hot path.
"""

import bisect
import dataclasses
import importlib.util
import itertools
import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import pytest

from pretraining_llm_tpu.config import ObservabilityConfig, get_preset
from pretraining_llm_tpu.frontend.admission import RejectedBusy
from pretraining_llm_tpu.frontend.gateway import ServingGateway
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.capacity import DecisionLog
from pretraining_llm_tpu.observability.events import EventBus, json_line, sanitize_record
from pretraining_llm_tpu.observability.sketches import (
    DigestSketch,
    WindowedCounts,
    WindowedSketch,
)
from pretraining_llm_tpu.observability.slo import (
    SLOEngine,
    default_slo_classes,
)
from pretraining_llm_tpu.observability.goodput import CATEGORIES, GoodputAccountant
from pretraining_llm_tpu.observability.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
    MetricsRegistry,
    log_buckets,
)
from pretraining_llm_tpu.observability.spans import SpanRecorder
from pretraining_llm_tpu.observability.export import (
    lint_exposition,
    prometheus_lines,
    write_textfile,
)
from pretraining_llm_tpu.observability.tracing import (
    RequestTrace,
    Tracer,
    format_traceparent,
    parse_traceparent,
)
from pretraining_llm_tpu.observability.device import CompileWatcher
from pretraining_llm_tpu.observability.hub import ObservabilityHub
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector
from pretraining_llm_tpu.training.metrics import MetricsLogger, Throughput
from pretraining_llm_tpu.training.trainer import Trainer
from pretraining_llm_tpu.utils.profiling import StepProfiler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS_REPORT = os.path.join(REPO, "scripts", "obs_report.py")
SUPERVISOR = os.path.join(REPO, "scripts", "supervisor.py")


# ------------------------------------------------------------- events


def test_event_bus_stamps_and_sinks(tmp_path):
    path = tmp_path / "events.jsonl"
    seen = []
    bus = EventBus(str(path))
    bus.subscribe(seen.append)
    bus.emit("run_start", step=0, total=10)
    bus.emit("eval", step=4, dur_s=0.5, val_loss=3.2)
    bus.close()
    # Reopens on demand after close (trainer releases the fd per exit path).
    bus.emit("run_end", exit_reason="completed")
    bus.close()

    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["event"] for r in lines] == ["run_start", "eval", "run_end"]
    assert [r["seq"] for r in lines] == [0, 1, 2]
    for rec in lines:
        assert isinstance(rec["t_wall"], float)
        assert isinstance(rec["t_mono"], float)
    assert lines[0]["step"] == 0 and lines[0]["total"] == 10
    assert lines[1]["dur_s"] == 0.5
    assert len(seen) == 3  # subscribers fire even with the sink closed


def test_event_bus_in_memory_and_thread_safe():
    bus = EventBus("")  # no sink
    seen = []
    bus.subscribe(seen.append)

    def emit_many():
        for _ in range(50):
            bus.emit("step_window", step=1, steps=1, dur_s=0.001)

    threads = [threading.Thread(target=emit_many) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(seen) == 200
    assert sorted(r["seq"] for r in seen) == list(range(200))


def test_sanitize_record_maps_nonfinite():
    rec = sanitize_record({"loss": float("nan"), "g": float("inf"), "ok": 1.5})
    assert rec["loss"] is None and rec["loss_nonfinite"] == "nan"
    assert rec["g"] is None and rec["g_nonfinite"] == "inf"
    assert rec["ok"] == 1.5
    # json_line output is strict JSON even for hostile records.
    parsed = json.loads(json_line({"a": float("-inf")}))
    assert parsed["a"] is None and parsed["a_nonfinite"] == "-inf"


# ------------------------------------------- metrics: NaN regression + window


def test_metrics_logger_nan_loss_stays_valid_jsonl(tmp_path):
    """Regression: json.dumps' default emits a bare ``NaN`` token — invalid
    JSON that corrupted the metrics stream exactly when the anomaly
    detector was logging a NaN loss."""
    path = tmp_path / "metrics.jsonl"
    logger = MetricsLogger(str(path))
    logger.log({"step": 3, "loss": float("nan"), "mfu": 0.4})
    logger.log({"step": 4, "loss": 2.5})
    logger.close()
    lines = path.read_text().splitlines()
    parsed = [json.loads(l) for l in lines]  # every line must parse
    assert parsed[0]["loss"] is None
    assert parsed[0]["loss_nonfinite"] == "nan"
    assert parsed[0]["mfu"] == 0.4
    assert parsed[1]["loss"] == 2.5
    assert "NaN" not in lines[0]


def test_throughput_window_guards_zero_dt(monkeypatch):
    cfg = get_preset("tiny")
    tp = Throughput(cfg.model, n_chips=1)
    tp.reset_clock()
    tp.tick(64)
    # Freeze the clock at the window start: dt == 0 must yield {} rather
    # than a ZeroDivisionError.
    frozen = tp._last_time
    monkeypatch.setattr("time.perf_counter", lambda: frozen)
    assert tp.window() == {}
    # No steps observed -> no window either.
    monkeypatch.undo()
    tp.reset_clock()
    assert tp.window() == {}


# --------------------------------------------------------------- spans


def test_spans_nest_summarize_and_export(tmp_path):
    rec = SpanRecorder()
    with rec.span("outer"):
        with rec.span("inner"):
            pass
        with rec.span("inner"):
            pass
    summary = rec.summary()
    assert summary["outer"]["count"] == 1
    assert summary["inner"]["count"] == 2
    assert summary["outer"]["total_s"] >= summary["inner"]["total_s"]

    trace = rec.to_chrome_trace()
    assert trace["displayTimeUnit"] == "ms"
    events = trace["traceEvents"]
    assert len(events) == 3
    by_name = {}
    for e in events:
        assert e["ph"] == "X"
        assert set(e) >= {"name", "ts", "dur", "pid", "tid", "args"}
        by_name.setdefault(e["name"], []).append(e)
    assert by_name["inner"][0]["args"]["depth"] == 1
    assert by_name["outer"][0]["args"]["depth"] == 0
    # Containment: outer's window covers both inners.
    outer = by_name["outer"][0]
    for inner in by_name["inner"]:
        assert outer["ts"] <= inner["ts"] + 1  # float-us slack
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1

    path = rec.export(str(tmp_path / "spans.trace.json"))
    loaded = json.load(open(path))
    assert len(loaded["traceEvents"]) == 3


def test_spans_bounded_memory():
    rec = SpanRecorder(max_events=2)
    for _ in range(5):
        with rec.span("s"):
            pass
    assert rec.summary()["s"]["count"] == 2
    assert rec.dropped == 3
    assert rec.to_chrome_trace()["otherData"]["dropped_spans"] == 3


def test_span_survives_exception():
    rec = SpanRecorder()
    with pytest.raises(RuntimeError):
        with rec.span("failing"):
            raise RuntimeError("boom")
    assert rec.summary()["failing"]["count"] == 1


# ------------------------------------------------------------- goodput


def _ev(kind, t, **fields):
    return {"event": kind, "t_wall": float(t), "t_mono": float(t), **fields}


def test_goodput_fold_rollback_and_relaunch():
    """Synthetic two-run stream: a rollback makes re-run steps replay (not
    productive), a relaunch gap is idle, and every category sums to the
    total wall-clock exactly."""
    stream = [
        _ev("run_start", 0.0, step=0, total=20),
        _ev("step_window", 10.0, step=10, steps=10, dur_s=10.0),   # all new
        _ev("ckpt_save", 11.0, step=10, dur_s=1.0),
        _ev("rollback", 13.0, step=10, from_step=10, to_step=5, dur_s=2.0),
        _ev("step_window", 18.0, step=10, steps=5, dur_s=5.0),     # all replay
        _ev("step_window", 23.0, step=15, steps=5, dur_s=5.0),     # all new
        _ev("run_end", 24.0, exit_reason="preempted"),
        # supervisor gap, then relaunch resumes at step 15
        _ev("relaunch", 25.0, rc=43, why="preempted"),
        _ev("run_start", 30.0, step=15, total=20),
        _ev("eval", 33.0, step=15, dur_s=2.0, val_loss=3.0),
        _ev("run_end", 35.0, exit_reason="completed"),
    ]
    s = GoodputAccountant.fold(stream)
    cats = s["categories"]
    assert cats["productive"] == pytest.approx(15.0)
    assert cats["replay"] == pytest.approx(5.0)
    assert cats["checkpoint"] == pytest.approx(1.0)
    assert cats["restore"] == pytest.approx(2.0)
    assert cats["eval"] == pytest.approx(2.0)
    assert cats["idle"] == pytest.approx(5.0)  # run_end@24+relaunch@25 .. 30
    assert s["total_s"] == pytest.approx(35.0)
    assert sum(cats.values()) == pytest.approx(s["total_s"])  # exact closure
    assert s["goodput"] == pytest.approx(15.0 / 35.0)
    assert s["runs"] == 2 and s["rollbacks"] == 1
    assert s["max_step"] == 15
    assert s["exit_reason"] == "completed"

    # The same stream without the rollback detour is strictly better.
    clean = [
        _ev("run_start", 0.0, step=0, total=15),
        _ev("step_window", 10.0, step=10, steps=10, dur_s=10.0),
        _ev("ckpt_save", 11.0, step=10, dur_s=1.0),
        _ev("step_window", 16.0, step=15, steps=5, dur_s=5.0),
        _ev("run_end", 17.0, exit_reason="completed"),
    ]
    assert GoodputAccountant.fold(clean)["goodput"] > s["goodput"]


def test_goodput_partial_window_split():
    """A window straddling the high-water mark splits pro-rata."""
    stream = [
        _ev("run_start", 0.0, step=10, total=20),  # resumed at step 10
        # 8 steps ending at 14: only 4 are past the hwm of 10.
        _ev("step_window", 8.0, step=14, steps=8, dur_s=8.0),
    ]
    cats = GoodputAccountant.fold(stream)["categories"]
    assert cats["productive"] == pytest.approx(4.0)
    assert cats["replay"] == pytest.approx(4.0)


def test_goodput_ignores_unstamped_and_unknown():
    s = GoodputAccountant.fold([
        {"event": "step_window", "steps": 5, "dur_s": 5.0},  # no t_wall
        _ev("device_memory", 1.0, max_bytes_in_use=10.0),    # unknown to fold
        _ev("step_window", 2.0, step=5, steps=5, dur_s=1.0),
    ])
    assert s["categories"]["productive"] == pytest.approx(1.0)


# ---------------------------------------------------------- prometheus


def test_prometheus_lines_format():
    out = prometheus_lines(
        {"loss": 2.5, "mfu": 0.43, "note": "skip-me", "ok": True,
         "bad value!": 1.0, "nan_metric": float("nan")},
        labels={"run": 'a"b\n'},
    )
    lines = out.splitlines()
    assert '# TYPE pllm_loss gauge' in lines
    assert any(l.startswith('pllm_loss{run="a\\"b\\n"} 2.5') for l in lines)
    assert any(l.startswith("pllm_bad_value_{") for l in lines)  # sanitized
    assert any(" NaN" in l for l in lines)
    assert any(l.startswith("pllm_ok{") and l.endswith(" 1.0") for l in lines)
    assert "note" not in out  # strings skipped
    # Every non-comment line is name{labels} value.
    for line in lines:
        if line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        assert name and (val == "NaN" or float(val) is not None)


def test_prometheus_textfile_atomic_write(tmp_path):
    path = tmp_path / "metrics.prom"
    write_textfile(str(path), {"goodput": 0.9}, stamp=True)
    body = path.read_text()
    assert "pllm_goodput" in body
    assert "pllm_last_write_seconds" in body
    assert not list(tmp_path.glob("*.tmp"))  # replaced, not left behind
    write_textfile(str(path), {"goodput": 0.8}, stamp=False)
    assert "0.8" in path.read_text()


# ------------------------------------------------------ compile watcher


def test_compile_watcher_warm_line():
    bus = EventBus("")
    seen = []
    bus.subscribe(seen.append)
    w = CompileWatcher(bus)
    w.note_compile(2.0)  # cold: the initial jit, counted but not an event
    assert w.summary()["compiles"] == 1
    assert w.summary()["recompiles"] == 0
    w.mark_warm(step=1)
    w.at_step(4)
    w.note_compile(0.5)  # warm: a recompile event
    s = w.summary()
    assert s["recompiles"] == 1 and s["recompile_s"] == pytest.approx(0.5)
    assert [e["event"] for e in seen] == ["recompile"]
    assert seen[0]["step"] == 4 and seen[0]["dur_s"] == 0.5


def test_compile_watcher_suppress_scopes_off_path_compiles():
    bus = EventBus("")
    seen = []
    bus.subscribe(seen.append)
    w = CompileWatcher(bus)
    w.mark_warm(step=1)
    with w.suppress():
        w.note_compile(1.0)  # eval-loop first jit: counted, not an event
    w.note_compile(0.25)  # bare step path: a real recompile
    s = w.summary()
    assert s["compiles"] == 2
    assert s["recompiles"] == 1
    assert [e["event"] for e in seen] == ["recompile"]


def test_compile_watcher_listener_registration_roundtrip():
    """start() hooks jax.monitoring; a jit compile lands in the counters;
    stop() deactivates (no further counting)."""
    import jax
    import jax.numpy as jnp

    w = CompileWatcher().start()
    before = w.summary()["compiles"]

    @jax.jit
    def f(x):
        return x * 2 + 1

    f(jnp.arange(7)).block_until_ready()
    assert w.summary()["compiles"] > before
    w.stop()
    after = w.summary()["compiles"]

    @jax.jit
    def g(x):
        return x * 3 - 1

    g(jnp.arange(9)).block_until_ready()
    assert w.summary()["compiles"] == after


# ------------------------------------------------------------ profiler


def test_step_profiler_close_idempotent_and_exception_safe(monkeypatch):
    calls = {"start": 0, "stop": 0}
    import jax

    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.__setitem__("start", calls["start"] + 1)
    )

    def stop():
        calls["stop"] += 1
        if calls["stop"] == 1:
            raise RuntimeError("backend refused")

    monkeypatch.setattr(jax.profiler, "stop_trace", stop)
    prof = StepProfiler("logs", start_step=0, n_steps=10)
    prof.step(0)
    assert calls["start"] == 1
    prof.close()  # stop raises: swallowed, trace marked closed
    prof.close()  # idempotent: no second stop call
    assert calls["stop"] == 1


@pytest.mark.slow
def test_exception_mid_profile_window_stops_trace(tmp_path, monkeypatch):
    """An exception inside the profiled step window must still stop the
    trace on the way out of train() (satellite c)."""
    calls = {"start": 0, "stop": 0}
    import jax

    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.__setitem__("start", calls["start"] + 1)
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.__setitem__("stop", calls["stop"] + 1)
    )
    cfg = get_preset("tiny")
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, train_steps=10, log_interval=100, eval_interval=0,
        checkpoint_interval=0, save_final=False,
        checkpoint_dir=str(tmp_path / "ck"),
        profile_dir=str(tmp_path / "prof"), profile_start=1, profile_steps=50,
    ))
    t = Trainer(cfg, synthetic_data=True, resume=False)
    real_step = t.step_fn
    counter = {"n": 0}

    def exploding(state, batch):
        counter["n"] += 1
        if counter["n"] == 3:
            raise RuntimeError("mid-window boom")
        return real_step(state, batch)

    t.step_fn = exploding
    with pytest.raises(RuntimeError, match="mid-window boom"):
        t.train()
    assert calls["start"] == 1
    assert calls["stop"] == 1  # finally closed the in-flight capture


# --------------------------------------------------------- hot-path purity


@pytest.mark.slow
def test_no_device_syncs_between_log_boundaries(tmp_path):
    """Device→host syncs (float conversions of step metrics, explicit
    block_until_ready) must happen only at log boundaries — the device
    queue stays full between logs (acceptance: no new hot-path syncs)."""
    import jax

    cfg = get_preset("tiny")
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, train_steps=8, log_interval=4, eval_interval=0,
        checkpoint_interval=0, save_final=False,
        checkpoint_dir=str(tmp_path / "ck"),
    ))
    t = Trainer(cfg, synthetic_data=True, resume=False)

    conversions = []

    class Tracked:
        """Stands in for a device scalar: float() is the sync."""

        def __init__(self, val, step_no):
            self._val = val
            self._step_no = step_no

        def __float__(self):
            conversions.append(self._step_no)
            return float(self._val)

    real_step = t.step_fn
    step_no = {"n": 0}

    def wrapped(state, batch):
        state, metrics = real_step(state, batch)
        step_no["n"] += 1
        return state, {k: Tracked(v, step_no["n"]) for k, v in metrics.items()}

    t.step_fn = wrapped

    bur_calls = []
    real_bur = jax.block_until_ready
    jax.block_until_ready = lambda x: (bur_calls.append(1), real_bur(x))[1]
    try:
        t.train()
    finally:
        jax.block_until_ready = real_bur

    # Metrics were converted ONLY for the two boundary steps (4 and 8).
    assert conversions, "log boundaries must sync metrics"
    assert set(conversions) == {4, 8}, sorted(set(conversions))
    assert bur_calls == []  # no explicit syncs anywhere on the loop


# -------------------------------------------------------- trainer e2e


def _obs_config(tmp_path, **train_kw):
    cfg = get_preset("tiny")
    train_kw.setdefault("train_steps", 8)
    train_kw.setdefault("log_interval", 2)
    train_kw.setdefault("eval_interval", 4)
    train_kw.setdefault("eval_iters", 1)
    train_kw.setdefault("checkpoint_interval", 4)
    train_kw.setdefault("checkpoint_dir", str(tmp_path / "ck"))
    train_kw.setdefault("metrics_path", str(tmp_path / "metrics.jsonl"))
    return cfg.replace(
        train=dataclasses.replace(cfg.train, **train_kw),
        obs=ObservabilityConfig(
            events_path=str(tmp_path / "events.jsonl"),
            spans_path=str(tmp_path / "spans.trace.json"),
            prometheus_path=str(tmp_path / "metrics.prom"),
        ),
    )


@pytest.mark.slow
def test_trainer_emits_coherent_event_stream(tmp_path):
    cfg = _obs_config(tmp_path)
    t = Trainer(cfg, synthetic_data=True, resume=False)
    t.train()

    events = [
        json.loads(l) for l in (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    kinds = [e["event"] for e in events]
    assert kinds[0] == "run_start"
    assert kinds[-1] == "run_end"
    for expected in ("step_window", "eval", "ckpt_save"):
        assert expected in kinds, kinds
    # The eval loop's first jit is expected off-path compile, not a
    # step-loop recompile storm.
    assert "recompile" not in kinds
    # run_end carries the summary.
    end = events[-1]
    assert end["exit_reason"] == "completed"
    assert 0.0 <= end["goodput"] <= 1.0
    assert "compile" in end and end["compile"]["compiles"] >= 1
    assert "ckpt_save" in end["spans"]

    # Offline fold closes the budget: categories sum to total within 1%.
    summary = GoodputAccountant.fold(events)
    total = summary["total_s"]
    assert total > 0
    assert sum(summary["categories"].values()) == pytest.approx(
        total, rel=0.01
    )
    assert summary["categories"]["productive"] > 0
    assert summary["exit_reason"] == "completed"

    # Metrics records merged the live goodput fraction at log boundaries.
    metrics = [
        json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert any("goodput" in m for m in metrics)

    # Spans exported as valid Chrome trace; checkpoint-layer spans landed
    # in the hub's recorder via the module-default slot.
    trace = json.load(open(tmp_path / "spans.trace.json"))
    names = {e["name"] for e in trace["traceEvents"]}
    assert "ckpt_save" in names
    assert "checkpoint/write_leaves" in names

    # Prometheus textfile holds the final goodput gauge.
    prom = (tmp_path / "metrics.prom").read_text()
    assert "pllm_goodput" in prom


@pytest.mark.slow
def test_rollback_lowers_goodput_end_to_end(tmp_path):
    """Inject a NaN fault -> anomaly rollback; the event stream must carry
    the rollback and the fold must show replay time + goodput < 1 even
    though the run completes."""
    from pretraining_llm_tpu.config import ResilienceConfig

    cfg = _obs_config(
        tmp_path, train_steps=12, log_interval=2, eval_interval=0,
        checkpoint_interval=2,
    )
    cfg = cfg.replace(resilience=ResilienceConfig(
        anomaly_detection=True, faults="nan@5", cooldown_steps=2,
        skip_batches=1,
    ))
    t = Trainer(cfg, synthetic_data=True, resume=False)
    t.train()
    assert t.exit_reason == "completed"

    events = [
        json.loads(l) for l in (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    kinds = [e["event"] for e in events]
    assert "fault_injected" in kinds
    assert "rollback" in kinds
    # The restore's fresh device_put programs must not masquerade as
    # step-loop recompiles (trainer wraps handle() in suppressed_compiles).
    assert "recompile" not in kinds
    rb = next(e for e in events if e["event"] == "rollback")
    assert rb["to_step"] < rb["from_step"]
    assert rb["dur_s"] > 0

    summary = GoodputAccountant.fold(events)
    assert summary["rollbacks"] == 1
    assert summary["categories"]["replay"] > 0  # re-run steps not productive
    assert summary["categories"]["restore"] > 0
    assert summary["goodput"] < 1.0
    assert summary["max_step"] == 12


@pytest.mark.slow
def test_trainer_event_stream_on_exception(tmp_path):
    cfg = _obs_config(tmp_path, eval_interval=0, checkpoint_interval=0,
                      save_final=False)
    t = Trainer(cfg, synthetic_data=True, resume=False)
    real_step = t.step_fn
    n = {"c": 0}

    def exploding(state, batch):
        n["c"] += 1
        if n["c"] == 3:
            raise ValueError("boom")
        return real_step(state, batch)

    t.step_fn = exploding
    with pytest.raises(ValueError):
        t.train()
    events = [
        json.loads(l) for l in (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    kinds = [e["event"] for e in events]
    assert "failure" in kinds
    assert kinds[-1] == "run_end"
    assert events[-1]["exit_reason"] == "exception"


# ------------------------------------------------------- offline analyzer


def _run_report(*argv):
    return subprocess.run(
        [sys.executable, OBS_REPORT, *argv],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_obs_report_over_synthetic_stream(tmp_path):
    events_path = tmp_path / "events.jsonl"
    stream = [
        _ev("run_start", 0.0, step=0, total=10),
        _ev("step_window", 5.0, step=5, steps=5, dur_s=5.0),
        _ev("ckpt_save", 6.0, step=5, dur_s=1.0),
        _ev("step_window", 11.0, step=10, steps=5, dur_s=5.0),
        _ev("run_end", 12.0, exit_reason="completed"),
    ]
    events_path.write_text("".join(json.dumps(e) + "\n" for e in stream))
    metrics_path = tmp_path / "metrics.jsonl"
    metrics_path.write_text(
        json.dumps({"step": 5, "loss": 3.0, "step_ms": 100.0}) + "\n"
        + json.dumps({"step": 10, "loss": 2.5, "step_ms": 120.0}) + "\n"
    )
    res = _run_report("--json", "--strict", str(events_path), str(metrics_path))
    assert res.returncode == 0, res.stderr
    report = json.loads(res.stdout)
    assert report["bad_lines"] == 0
    assert report["goodput"]["goodput"] == pytest.approx(10.0 / 12.0)
    cats = report["goodput"]["categories"]
    assert sum(cats.values()) == pytest.approx(report["goodput"]["total_s"], rel=0.01)
    assert report["step_time"]["count"] == 2
    assert report["step_time"]["mean_ms"] == pytest.approx(110.0)
    assert report["event_counts"]["step_window"] == 2
    assert any(t["event"] == "ckpt_save" for t in report["timeline"])
    # Human output renders without error too.
    res_txt = _run_report(str(events_path), str(metrics_path))
    assert res_txt.returncode == 0
    assert "goodput" in res_txt.stdout


def test_obs_report_strict_fails_on_bad_lines(tmp_path):
    path = tmp_path / "metrics.jsonl"
    path.write_text('{"step": 1, "loss": 2.0}\n{"step": 2, "loss": NaN}\n')
    lax = _run_report(str(path))
    assert lax.returncode == 0  # reported, not fatal
    strict = _run_report("--strict", str(path))
    assert strict.returncode == 1
    assert "unparseable" in strict.stderr


def test_obs_report_imports_without_jax(tmp_path):
    """The analyzer must run where the training stack doesn't: block every
    jax import in a fresh interpreter (including sitecustomize's
    pre-import) and run a full report."""
    path = tmp_path / "e.jsonl"
    path.write_text(json.dumps(_ev("run_start", 0.0, step=0)) + "\n")
    code = f"""
import sys
for name in list(sys.modules):
    if name == "jax" or name.startswith("jax."):
        del sys.modules[name]
class _Block:
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("jax blocked for obs_report")
sys.meta_path.insert(0, _Block())
import importlib.util
spec = importlib.util.spec_from_file_location("obs_report", {OBS_REPORT!r})
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
sys.argv = ["obs_report", "--json", {str(path)!r}]
sys.exit(mod.main())
"""
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True
    )
    assert res.returncode == 0, res.stderr
    assert json.loads(res.stdout)["n_events"] == 1


# ----------------------------------------------------------- supervisor


def test_supervisor_writes_relaunch_events(tmp_path):
    events_path = tmp_path / "sup_events.jsonl"
    child = "import sys; sys.exit(7)"
    res = subprocess.run(
        [
            sys.executable, SUPERVISOR,
            "--max-restarts", "1", "--backoff-base", "0.01",
            "--events", str(events_path),
            "--", sys.executable, "-c", child,
        ],
        capture_output=True, text=True,
    )
    assert res.returncode == 7
    events = [json.loads(l) for l in events_path.read_text().splitlines()]
    kinds = [e["event"] for e in events]
    assert kinds == ["relaunch", "failure"]
    assert events[0]["rc"] == 7 and events[0]["why"].startswith("crash")
    assert events[1]["why"] == "restart_budget"
    for e in events:
        assert "t_wall" in e and "t_mono" in e and e["supervisor"] is True


# ---------------------------------------------------------------- config


def test_observability_config_validates_and_overrides():
    with pytest.raises(ValueError):
        ObservabilityConfig(device_memory_interval=-1)
    cfg = get_preset("tiny").with_overrides({
        "obs.events_path": "/tmp/e.jsonl",
        "obs.device_memory_interval": 3,
    })
    assert cfg.obs.events_path == "/tmp/e.jsonl"
    assert cfg.obs.device_memory_interval == 3
    # JSON round-trip carries the obs block.
    raw = json.loads(cfg.to_json()) if hasattr(cfg, "to_json") else None
    if raw is not None:
        assert raw["obs"]["events_path"] == "/tmp/e.jsonl"


# ------------------------------------------------- typed metrics registry


def test_registry_render_is_lint_clean():
    reg = MetricsRegistry(prefix="pllm_serving_")
    reg.counter("requests_terminal_total", "terminal requests", status="done").inc(3)
    reg.counter("requests_terminal_total", status="cancelled").inc()
    reg.gauge("queue_depth", "waiting requests").set(2)
    h = reg.histogram("ttft_seconds", "time to first token")
    for v in (0.001, 0.02, 0.3, 4.0):
        h.observe(v)
    text = reg.render(extra_gauges={"active_requests": 1, "note": "skip-me"})
    assert lint_exposition(text) == [], lint_exposition(text)
    # One TYPE header covers both labeled counter children.
    assert text.count("# TYPE pllm_serving_requests_terminal_total counter") == 1
    assert 'pllm_serving_requests_terminal_total{status="done"} 3.0' in text
    assert "pllm_serving_ttft_seconds_count 4.0" in text
    assert 'le="+Inf"' in text
    # Extra gauges ride along under the prefix; non-numeric values skipped.
    assert "# TYPE pllm_serving_active_requests gauge" in text
    assert "note" not in text


def test_registry_enforces_naming_and_kinds():
    reg = MetricsRegistry(prefix="p_")
    with pytest.raises(ValueError, match="_total"):
        reg.counter("requests")
    with pytest.raises(ValueError, match="collides"):
        reg.histogram("latency_bucket")
    # Re-registering the same name as another kind is an error.
    reg2 = MetricsRegistry()
    reg2.gauge("x")
    with pytest.raises(ValueError, match="already registered as gauge"):
        reg2.histogram("x")
    c = reg.counter("ok_total")
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    # Get-or-create: the same (name, labels) returns the same object.
    assert reg.counter("ok_total") is c
    with pytest.raises(ValueError, match="sorted"):
        Histogram("h", {}, buckets=(2.0, 1.0))
    assert log_buckets(0.001, 0.01)[-1] >= 0.01
    with pytest.raises(ValueError):
        log_buckets(0.0, 1.0)


def test_histogram_percentile_vs_nearest_rank():
    """Property: the bucket-interpolated quantile differs from the exact
    nearest-rank quantile (loadgen's _percentile) by at most the width of
    the bucket the exact value fell in — the documented error bound."""
    from pretraining_llm_tpu.frontend.loadgen import _percentile

    rng = random.Random(7)
    for trial in range(5):
        vals = sorted(
            min(80.0, rng.expovariate(1.0 / 0.05) + rng.random() * 0.001)
            for _ in range(257)
        )
        h = Histogram("lat", {}, buckets=DEFAULT_LATENCY_BUCKETS)
        for v in vals:
            h.observe(v)
        for q in (0.0, 0.5, 0.9, 0.99, 1.0):
            exact = _percentile(vals, q)
            est = h.percentile(q)
            # Width of the bucket containing the exact value.
            bounds = (0.0,) + DEFAULT_LATENCY_BUCKETS
            i = next(
                (j for j in range(1, len(bounds)) if exact <= bounds[j]),
                len(bounds) - 1,
            )
            width = bounds[i] - bounds[i - 1]
            assert abs(est - exact) <= width + 1e-12, (trial, q, exact, est)
            assert vals[0] <= est <= vals[-1]  # clamped to the data range


def test_histogram_low_outliers_never_lost():
    h = Histogram("h", {}, buckets=(0.1, 1.0))
    h.observe(-0.5)  # clock artifact
    h.observe(0.0)
    h.observe(5.0)  # overflow
    assert h.count == 3
    samples = dict(
        ((name, labels["le"]), v) for name, labels, v in h.samples()
        if name.endswith("_bucket")
    )
    assert samples[("h_bucket", "0.1")] == 2.0
    assert samples[("h_bucket", "+Inf")] == 3.0
    # Estimates stay inside the observed range even with outliers on both
    # sides of the bucket bounds.
    assert -0.5 <= h.percentile(0.0) <= 0.1
    assert h.percentile(1.0) == pytest.approx(5.0)


def test_prometheus_lines_typed_counters():
    text = prometheus_lines(
        {"requests": 4, "depth": 2},
        prefix="p_",
        types={"requests": "counter"},
    )
    assert "# TYPE p_requests_total counter" in text
    assert "p_requests_total 4.0" in text
    assert "# TYPE p_depth gauge" in text
    assert lint_exposition(text) == []
    with pytest.raises(ValueError, match="unsupported series type"):
        prometheus_lines({"x": 1}, types={"x": "histogram"})


def test_lint_exposition_flags_contract_violations():
    assert lint_exposition("") == []
    bad = {
        "counter w/o _total": "# TYPE a_requests counter\na_requests 1.0\n",
        "gauge named _total": "# TYPE a_x_total gauge\na_x_total 1.0\n",
        "TYPE after sample": "a_x 1.0\n# TYPE a_x gauge\na_x 2.0\n",
        "duplicate TYPE": "# TYPE a_x gauge\n# TYPE a_x gauge\na_x 1.0\n",
        "untyped sample": "# TYPE a_x gauge\na_x 1.0\na_y 2.0\n",
        "unparseable": "# TYPE a_x gauge\na_x one\n",
        "no +Inf": (
            "# TYPE h histogram\nh_bucket{le=\"1\"} 1.0\n"
            "h_sum 0.5\nh_count 1.0\n"
        ),
        "not cumulative": (
            "# TYPE h histogram\nh_bucket{le=\"1\"} 2.0\n"
            "h_bucket{le=\"+Inf\"} 1.0\nh_sum 0.5\nh_count 1.0\n"
        ),
        "+Inf != count": (
            "# TYPE h histogram\nh_bucket{le=\"1\"} 1.0\n"
            "h_bucket{le=\"+Inf\"} 2.0\nh_sum 0.5\nh_count 3.0\n"
        ),
        "missing _sum": (
            "# TYPE h histogram\nh_bucket{le=\"1\"} 1.0\n"
            "h_bucket{le=\"+Inf\"} 1.0\nh_count 1.0\n"
        ),
    }
    for why, text in bad.items():
        assert lint_exposition(text), f"lint missed: {why}"
    good = (
        "# HELP h latency\n# TYPE h histogram\n"
        'h_bucket{le="0.1"} 1.0\nh_bucket{le="1"} 3.0\n'
        'h_bucket{le="+Inf"} 4.0\nh_sum 2.5\nh_count 4.0\n'
        "# TYPE a_total counter\na_total 7.0\n"
    )
    assert lint_exposition(good) == []


# -------------------------------------------------------- request tracing


def test_traceparent_parse_and_format():
    tid, sid = "0af7651916cd43dd8448eb211c80319c", "b7ad6b7169203331"
    ctx = parse_traceparent(f"00-{tid}-{sid}-01")
    assert ctx.trace_id == tid and ctx.span_id == sid and ctx.sampled
    assert not parse_traceparent(f"00-{tid}-{sid}-00").sampled
    assert format_traceparent(ctx) == f"00-{tid}-{sid}-01"
    # Uppercase hex is tolerated (lowered), per the robustness clause.
    assert parse_traceparent(f"00-{tid.upper()}-{sid}-01") is not None
    for bad in (
        None, "", "garbage", f"00-{tid}-{sid}", f"ff-{tid}-{sid}-01",
        f"00-{'0' * 32}-{sid}-01", f"00-{tid}-{'0' * 16}-01",
        f"00-{tid[:-1]}-{sid}-01",
    ):
        assert parse_traceparent(bad) is None, bad


def test_request_trace_tree_and_chrome_export():
    rec = SpanRecorder()
    tracer = Tracer(rec, sample=1.0, seed=11)
    tr = tracer.begin_request()
    t0 = tr.t0
    tr.span("req.queue", t0, t0 + 0.01, outcome="admitted")
    tr.event("req.first_token")
    assert tr.finish("done", n_tokens=4)
    assert not tr.finish("done")  # idempotent: one root per trace
    trace = rec.to_chrome_trace()
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    root = next(e for e in spans if e["name"] == "req.request")
    assert root["args"]["status"] == "done" and root["args"]["n_tokens"] == 4
    for child in spans:
        if child is not root:
            assert child["args"]["parent_span_id"] == root["args"]["span_id"]
        assert child["args"]["trace_id"] == tr.trace_id
    # Every request renders on its own named virtual track.
    names = [
        e["args"]["name"] for e in trace["traceEvents"] if e.get("ph") == "M"
    ]
    assert f"req {tr.trace_id[:12]}" in names
    assert trace["otherData"]["dropped_spans"] == 0


def test_tracer_sampling_and_inbound_join():
    rec = SpanRecorder()
    assert Tracer(rec, sample=0.0).begin_request() is None
    with pytest.raises(ValueError):
        Tracer(rec, sample=1.5)
    tracer = Tracer(rec, sample=0.0, seed=1)
    # An inbound sampled header overrides head-sampling (caller decided)...
    tid = "0af7651916cd43dd8448eb211c80319c"
    tr = tracer.begin_request(f"00-{tid}-b7ad6b7169203331-01")
    assert tr is not None and tr.trace_id == tid
    assert tr.parent_id == "b7ad6b7169203331"
    # ...and an inbound UNsampled header suppresses even sample=1.0.
    full = Tracer(rec, sample=1.0, seed=1)
    assert full.begin_request(f"00-{tid}-b7ad6b7169203331-00") is None
    # Seeded tracers mint deterministic ids.
    a = Tracer(SpanRecorder(), sample=1.0, seed=5).begin_request()
    b = Tracer(SpanRecorder(), sample=1.0, seed=5).begin_request()
    assert a.trace_id == b.trace_id and a.root_id == b.root_id


def test_span_recorder_surfaces_drops_in_trace():
    rec = SpanRecorder(max_events=2)
    for i in range(5):
        rec.record(f"s{i}", 0.0, 0.001)
    assert rec.dropped == 3
    trace = rec.to_chrome_trace()
    assert trace["otherData"]["dropped_spans"] == 3
    instants = [e for e in trace["traceEvents"] if e.get("ph") == "i"]
    assert instants and instants[-1]["name"] == "spans_dropped"
    assert instants[-1]["args"]["dropped"] == 3


def _request_trace_fixture(statuses):
    """A recorder holding one complete span tree per (status, idx)."""
    import time as _time

    rec = SpanRecorder()
    tracer = Tracer(rec, sample=1.0, seed=3)
    for status in statuses:
        tr = tracer.begin_request()
        # Backdate the trace so the synthetic child offsets below land
        # INSIDE the root span [t0, finish-time] — finish() reads the
        # real clock.
        tr.t0 = _time.perf_counter() - 0.25
        tr.marks["start"] = tr.t0
        t0 = tr.t0
        if status == "rejected":
            tr.span("req.admission", t0, t0 + 0.001, outcome="rejected")
            tr.finish("rejected", reason="busy")
            continue
        tr.span("req.admission", t0, t0 + 0.0005, outcome="admitted")
        tr.span("req.queue", t0, t0 + 0.02, outcome=status)
        if status == "done":
            tr.span("req.prefill", t0 + 0.02, t0 + 0.03, n_prompt=5)
            tr.span("req.window", t0 + 0.03, t0 + 0.08,
                    steps=4, host_blocked_s=0.01)
            tr.span("req.window", t0 + 0.06, t0 + 0.1,
                    steps=4, host_blocked_s=0.005)
            tr.event("req.first_token")
        tr.finish(status)
    return rec


def test_obs_report_slo_attribution(tmp_path):
    rec = _request_trace_fixture(["done", "done", "expired", "rejected"])
    trace_path = tmp_path / "trace.json"
    rec.export(str(trace_path))
    res = subprocess.run(
        [
            sys.executable, OBS_REPORT, "--json", "--strict", "--slo",
            "--trace", str(trace_path), "--slo_e2e_s", "0.001",
        ],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stderr
    serving = json.loads(res.stdout)["serving"]
    assert serving["n_traces"] == 4 and serving["problems"] == []
    assert serving["statuses"] == {"done": 2, "expired": 1, "rejected": 1}
    # Overlapping decode windows are unioned, not summed: [0.03, 0.1].
    done = [w for w in serving["waterfalls"] if w["status"] == "done"]
    for w in done:
        segs = w["segments"]
        assert segs["decode_s"] + segs["host_blocked_s"] == pytest.approx(
            0.07, rel=0.05
        )
        assert segs["host_blocked_s"] == pytest.approx(0.015, rel=0.05)
        # The decomposition sums to the root e2e (acceptance bound: 1%).
        assert abs(w["sum_error_s"]) <= 0.01 * w["e2e_s"] + 1e-9
    # Everything misses the absurd 1ms SLO; each miss names its dominant
    # segment (the "why we missed" attribution).
    assert len(serving["misses"]) == 4
    assert all(m["dominant_segment"] for m in serving["misses"])
    assert serving["tails"]["e2e_s"]["p99"] > 0


def test_obs_report_strict_fails_on_incomplete_tree(tmp_path):
    rec = _request_trace_fixture(["done"])
    trace = rec.to_chrome_trace()
    # Sever the tree: drop the terminal event.
    trace["traceEvents"] = [
        e for e in trace["traceEvents"] if e["name"] != "req.terminal"
    ]
    trace_path = tmp_path / "trace.json"
    trace_path.write_text(json.dumps(trace))
    res = subprocess.run(
        [
            sys.executable, OBS_REPORT, "--strict", "--slo",
            "--trace", str(trace_path),
        ],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 1
    assert "terminal" in res.stderr
    # Without --strict the same input reports and exits 0.
    lax = subprocess.run(
        [sys.executable, OBS_REPORT, "--slo", "--trace", str(trace_path)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert lax.returncode == 0


def test_obs_report_warns_on_dropped_spans(tmp_path):
    rec = SpanRecorder(max_events=1)
    tracer = Tracer(rec, sample=1.0, seed=3)
    tr = tracer.begin_request()
    tr.span("req.queue", tr.t0, tr.t0 + 0.01)
    tr.finish("done")  # terminal + root dropped: recorder is full
    trace_path = tmp_path / "trace.json"
    rec.export(str(trace_path))
    res = subprocess.run(
        [sys.executable, OBS_REPORT, "--trace", str(trace_path)],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0
    assert "dropped" in res.stderr


def test_hub_registry_typed_textfile(tmp_path):
    """The trainer hub's textfile export carries the typed series (window
    histogram + dropped-spans counter) alongside the flat gauges, and the
    whole body passes the exposition lint."""
    path = tmp_path / "m.prom"
    hub = ObservabilityHub(ObservabilityConfig(prometheus_path=str(path)))
    hub.spans.max_events = 0  # force drops
    with hub.spans.span("x"):
        pass
    hub.on_log_boundary(4, {"window_s": 1.25, "window_steps": 4},
                        {"loss": 3.0})
    text = path.read_text()
    assert lint_exposition(text) == [], lint_exposition(text)
    assert "# TYPE pllm_step_window_seconds histogram" in text
    assert "pllm_step_window_seconds_count 1.0" in text
    assert "pllm_spans_dropped_total 1.0" in text
    assert "# TYPE pllm_loss gauge" in text
    # The counter tracks the recorder's drop tally as a delta, not a reset.
    with hub.spans.span("y"):
        pass
    hub.on_log_boundary(8, {"window_s": 1.0, "window_steps": 4},
                        {"loss": 2.9})
    assert "pllm_spans_dropped_total 2.0" in path.read_text()


def test_hub_timed_event_attaches_fields():
    hub = ObservabilityHub(ObservabilityConfig())
    seen = []
    hub.bus.subscribe(seen.append)
    with hub.timed_event("eval", step=4) as ev:
        ev["val_loss"] = 3.25
    assert seen[-1]["event"] == "eval"
    assert seen[-1]["val_loss"] == 3.25
    assert seen[-1]["dur_s"] >= 0
    # The event fires even when the body raises (end-of-activity contract).
    with pytest.raises(RuntimeError):
        with hub.timed_event("eval", step=5):
            raise RuntimeError("eval died")
    assert seen[-1]["step"] == 5


# ------------------------------------------------ streaming sketches (SLO)


def _rank_error(sorted_vals, value, q):
    """Distance (in rank space) from q to the nearest rank that maps to
    ``value`` in the exact data — 0 when the estimate is exactly right."""
    lo = bisect.bisect_left(sorted_vals, value) / len(sorted_vals)
    hi = bisect.bisect_right(sorted_vals, value) / len(sorted_vals)
    if lo <= q <= hi:
        return 0.0
    return min(abs(q - lo), abs(q - hi))


_DISTS = {
    "uniform": lambda rng: rng.random(),
    "normal": lambda rng: rng.gauss(0.0, 1.0),
    "lognormal": lambda rng: rng.lognormvariate(0.0, 1.5),
}


@pytest.mark.parametrize("dist", sorted(_DISTS))
def test_digest_sketch_rank_error_bound(dist):
    """The documented accuracy contract: rank error at q stays under
    2*q*(1-q)/compression (plus one sample of slack) on synthetic
    streams, including a heavy-tailed one."""
    rng = random.Random(7)
    vals = [_DISTS[dist](rng) for _ in range(20000)]
    sk = DigestSketch(compression=64)
    for v in vals:
        sk.observe(v)
    sv = sorted(vals)
    for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 0.999):
        bound = 2.0 * q * (1.0 - q) / 64 + 1.0 / len(vals)
        err = _rank_error(sv, sk.quantile(q), q)
        assert err <= bound, f"q={q}: rank error {err} > bound {bound}"
    # Tails clamp to the exact observed extremes; mean is exact.
    assert sk.quantile(0.0) == min(vals)
    assert sk.quantile(1.0) == max(vals)
    exact_mean = sum(vals) / len(vals)
    assert abs(sk.mean - exact_mean) <= 1e-6 * max(1.0, abs(exact_mean))
    # Bounded size: the weight cap floors at 1 so the tails keep
    # singletons, but the centroid count stays O(compression), not O(N).
    assert len(sk.centroids()) <= 8 * 64


def test_digest_sketch_merge_order_invariance():
    """merge_all flattens + compresses ONCE, so every permutation of the
    replica sketches yields byte-identical centroids — the property that
    makes the fleet-wide digest well-defined."""
    rng = random.Random(3)
    vals = [rng.lognormvariate(0.0, 1.5) for _ in range(8000)]
    parts = [DigestSketch(compression=64) for _ in range(5)]
    for i, v in enumerate(vals):
        parts[i % 5].observe(v)
    merges = [
        DigestSketch.merge_all(p) for p in itertools.permutations(parts)
    ]
    ref = merges[0].centroids()
    for m in merges[1:]:
        assert m.centroids() == ref
    # The merged digest keeps the accuracy contract vs the union stream.
    sv = sorted(vals)
    for q in (0.5, 0.9, 0.99):
        bound = 2.0 * q * (1.0 - q) / 64 + 1.0 / len(vals)
        assert _rank_error(sv, merges[0].quantile(q), q) <= bound
    assert merges[0].count == len(vals)


def test_digest_sketch_wire_roundtrip():
    rng = random.Random(11)
    sk = DigestSketch(compression=32)
    for _ in range(5000):
        sk.observe(rng.gauss(5.0, 2.0))
    wire = json.loads(json.dumps(sk.to_dict()))  # actual JSON round-trip
    back = DigestSketch.from_dict(wire)
    assert back.centroids() == sk.centroids()
    assert back.count == sk.count
    assert (back.min, back.max) == (sk.min, sk.max)
    for q in (0.1, 0.5, 0.99):
        assert back.quantile(q) == sk.quantile(q)
    # Empty sketch round-trips too (a worker that saw no traffic yet).
    empty = DigestSketch.from_dict(json.loads(json.dumps(
        DigestSketch().to_dict()
    )))
    assert empty.count == 0
    assert empty.summary() == {"count": 0}


def test_windowed_sketch_rotation_under_fake_clock():
    t = [0.0]
    ws = WindowedSketch(window_s=6.0, buckets=3, clock=lambda: t[0])
    for _ in range(10):
        ws.observe(100.0)
    assert ws.count == 10
    t[0] = 3.0
    ws.observe(1.0)
    assert ws.count == 11  # both buckets still inside the window
    # Advance past the window: the old bucket falls off wholesale, the
    # lifetime total survives.
    t[0] = 7.9
    assert ws.count == 1
    assert ws.quantile(0.5) == 1.0
    t[0] = 100.0
    assert ws.count == 0
    assert ws.total_count == 11
    assert ws.summary()["count"] == 0


def test_windowed_counts_trailing_sums():
    t = [0.0]
    wc = WindowedCounts(horizon_s=40.0, bucket_s=1.0, clock=lambda: t[0])
    wc.add("events")
    wc.add("bad")
    t[0] = 10.0
    for _ in range(4):
        wc.add("events")
    # Trailing 5s sees only the recent burst; 40s sees everything.
    assert wc.sums(5.0) == {"events": 4.0}
    assert wc.sums(40.0) == {"events": 5.0, "bad": 1.0}
    # Past the horizon the old bucket is pruned on the next write, but
    # lifetime totals keep the full ledger.
    t[0] = 60.0
    wc.add("events")
    assert wc.sums(40.0) == {"events": 1.0}
    assert wc.totals == {"events": 6.0, "bad": 1.0}


# ------------------------------------------------------- live SLO engine


def _mk_slo(clock, **kw):
    bus = EventBus(clock=clock, wall=clock)
    dec = DecisionLog(bus=bus)
    kw.setdefault("window_scale", 0.01)
    slo = SLOEngine(
        classes=default_slo_classes(
            ttft_s=kw.pop("ttft_s", 0.5),
            e2e_s=kw.pop("e2e_s", 2.0),
            target=kw.pop("target", 0.99),
        ),
        bus=bus, decisions=dec, clock=clock, **kw,
    )
    alerts = []
    bus.subscribe(
        lambda r: alerts.append(r) if r.get("event") == "slo_alert" else None
    )
    return bus, dec, slo, alerts


def test_slo_fast_burn_fires_and_resolves_with_lineage():
    """Deterministic alert edge under a fake clock: a healthy prelude
    stays silent, a burst of slow requests trips fast_burn at the exact
    event where the burn crosses threshold on both windows, the firing
    event / decision record / resolved event share one alert_id, and
    rolling past the short window resolves without new traffic."""
    t = [100.0]
    bus, dec, slo, alerts = _mk_slo(lambda: t[0])
    for i in range(20):
        t[0] += 0.001
        bus.emit(
            "req_done", replica=0, trace_id=f"ok{i}",
            ttft_s=0.01, tpot_s=0.005, e2e_s=0.1, queue_wait_s=0.0,
        )
    assert alerts == []  # clean traffic never pages
    for i in range(5):
        t[0] += 0.001
        bus.emit(
            "req_done", replica=1, trace_id=f"slow{i}",
            ttft_s=5.0, tpot_s=0.1, e2e_s=6.0, queue_wait_s=0.5,
        )
    firing = [a for a in alerts if a["state"] == "firing"]
    fast = [a for a in firing if a["rule"] == "fast_burn"]
    assert len(fast) == 1
    al = fast[0]
    # burn = bad_frac / budget: fires at the 4th slow event, where
    # 4/24 bad over a 0.01 budget first clears the 14x threshold.
    assert al["slo_class"] == "interactive"
    assert al["severity"] == "page"
    assert al["trigger_trace_id"] == "slow3"
    assert al["trigger_replica"] == 1
    assert al["burn_short"] >= 14.0 and al["burn_long"] >= 14.0
    # Lineage: the decision ledger carries the SAME alert_id.
    decisions = [r for r in dec.tail() if r["decision"] == "slo_alert"]
    assert [d["alert_id"] for d in decisions].count(al["alert_id"]) == 1
    d = next(d for d in decisions if d["alert_id"] == al["alert_id"])
    assert d["rule"] == "fast_burn"
    assert d["trace_id"] == "slow3"
    # Replayability: the firing event is in the bus stream AFTER its
    # triggering terminal (seq order is the timeline).
    trigger_seq = max(
        r["seq"] for r in alerts if r.get("alert_id") == al["alert_id"]
    )
    assert trigger_seq >= al["seq"]
    # Roll the clock past the scaled short window with no traffic: the
    # snapshot tick resolves the alert and reuses the id.
    t[0] += 5.0
    snap = slo.snapshot()
    assert snap["alerts"]["active"] == []
    resolved = [a for a in alerts if a["state"] == "resolved"]
    assert {a["alert_id"] for a in resolved} >= {al["alert_id"]}
    r = next(a for a in resolved if a["alert_id"] == al["alert_id"])
    assert r["dur_s"] > 0
    # Lifetime budget ledger survives window rotation.
    cls = snap["classes"]["interactive"]
    assert cls["events"] == 25 and cls["bad"] == 5
    assert cls["bad_by_objective"] == {"ttft_s": 5}
    json.dumps(snap)  # the GET /slo body must be JSON-clean


def test_slo_cancelled_sketched_but_not_classified():
    t = [50.0]
    bus, _, slo, alerts = _mk_slo(lambda: t[0], target=0.9)
    t[0] += 0.01
    bus.emit("req_cancelled", replica=0, e2e_s=9.0, queue_wait_s=4.0)
    snap = slo.snapshot()
    # The latency lands in the distribution...
    assert snap["latency"]["fleet"]["e2e_s"]["count"] == 1
    # ...but burns no budget and fires nothing.
    assert snap["classes"]["interactive"]["events"] == 0
    assert alerts == []


def test_slo_client_visible_rejects_burn_availability():
    """fleet=True rejects (and untagged single-loop rejects) are
    availability-bad; replica-tagged internal refusals the router spills
    to a peer are not counted."""
    t = [50.0]
    bus, _, slo, alerts = _mk_slo(lambda: t[0], target=0.9)
    t[0] += 0.01
    bus.emit("req_rejected", replica=1, reason="busy")   # internal spill
    bus.emit("req_rejected", fleet=True, reason="placement")
    bus.emit("req_rejected", reason="queue_full")        # single-loop
    snap = slo.snapshot()
    cls = snap["classes"]["interactive"]
    assert cls["events"] == 2 and cls["bad"] == 2
    assert cls["bad_by_objective"] == {"availability": 2}
    # 2/2 bad over a 0.1 budget = burn 10 >= fast_burn threshold... but
    # target 0.9 gives threshold 14 > 10, so only slow_burn can fire.
    assert all(a["rule"] != "fast_burn" for a in alerts)


def test_slo_per_replica_sketches_split_the_fleet():
    t = [10.0]
    bus, _, slo, _ = _mk_slo(lambda: t[0])
    for i in range(50):
        t[0] += 0.001
        bus.emit("req_done", replica=i % 2, ttft_s=0.01 + (i % 2) * 1.0,
                 e2e_s=0.1, queue_wait_s=0.0, tpot_s=0.005)
    snap = slo.snapshot()
    per = snap["latency"]["replicas"]
    assert set(per) == {"0", "1"}
    assert per["0"]["ttft_s"]["p99"] < 0.1 < per["1"]["ttft_s"]["p99"]
    fleet = snap["latency"]["fleet"]["ttft_s"]
    assert fleet["count"] == 50
    # merged_sketch agrees with the snapshot's fleet summary.
    assert slo.merged_sketch("ttft_s").summary()["p99"] == fleet["p99"]


# ------------------------------- live SLO engine on a real serving fleet


_FLEET_CFG = dataclasses.replace(
    get_preset("tiny").model, compute_dtype="float32"
)


@pytest.fixture(scope="module")
def fleet_params():
    return transformer.init_params(_FLEET_CFG, jax.random.key(0))


def _slo_fleet(params, n=1, faults=None, bus=None):
    """A tiny real fleet sharing one in-memory bus. The SLO engine is
    NOT created here: tests attach it after warmup so jit-compile
    latency never pollutes the distributions or trips an alert."""
    if bus is None:
        bus = EventBus("")

    def factory():
        return ServingEngine(
            params, _FLEET_CFG, temperature=0.0, max_batch=2, n_blocks=24,
            block_size=8, steps_per_sched=4, pipeline_depth=2,
        )

    reps = [
        Replica(i, factory, bus=bus, fault_injector=faults)
        for i in range(n)
    ]
    router = Router(reps, bus=bus, eject_backoff_s=0.1)
    return bus, router


def _attach_slo(bus, router, *, ttft_s, e2e_s=120.0, target=0.99):
    dec = DecisionLog(bus=bus)
    slo = SLOEngine(
        classes=default_slo_classes(ttft_s=ttft_s, e2e_s=e2e_s, target=target),
        bus=bus, decisions=dec,
    )
    router.slo = slo
    alerts = []
    bus.subscribe(
        lambda r: alerts.append(r) if r.get("event") == "slo_alert" else None
    )
    return dec, slo, alerts


def test_fleet_reject_storm_trips_fast_burn(fleet_params):
    """Satellite: ``reject_storm`` deterministically trips fast_burn.
    With one replica the storm leaves the router nowhere to spill, so the
    client sees RejectedBusy and the bus sees a fleet-level
    ``req_rejected`` — availability burn 1/1 over a 0.01 budget = 100x,
    over threshold on the very first reject. No timing involved."""
    bus = EventBus("")
    faults = ServingFaultInjector("reject_storm@req1:r0", storm_rejects=3,
                                  bus=bus)
    # Injector and fleet share the bus: one seq timeline end to end.
    _, router = _slo_fleet(fleet_params, n=1, faults=faults, bus=bus)
    router.start()
    try:
        # Warm request: compiles, completes, and (as accepted submit #1)
        # arms the storm on its way in.
        status, toks, _ = router.submit([1, 2, 3], 4).result(timeout=300)
        assert status == "done" and len(toks) == 4
        dec, slo, alerts = _attach_slo(bus, router, ttft_s=2.0)

        with pytest.raises(RejectedBusy):
            router.submit([4, 5, 6], 4)
        fast = [a for a in alerts
                if a["rule"] == "fast_burn" and a["state"] == "firing"]
        assert len(fast) == 1, "first client-visible reject must page"
        al = fast[0]
        assert al["severity"] == "page"
        # Lineage: the alert_id ties the firing event to its entry in
        # the decision ledger (the replayable record of WHY we paged).
        # Burn 100x clears the slow_burn threshold too, so there may be
        # a second, slower-severity entry alongside.
        decs = [r for r in dec.tail() if r["decision"] == "slo_alert"]
        mine = [d for d in decs if d["alert_id"] == al["alert_id"]]
        assert len(mine) == 1 and mine[0]["rule"] == "fast_burn"

        # Drain the rest of the storm; then the fleet accepts again and
        # a healthy completion lands in the same budget ledger.
        for _ in range(2):
            with pytest.raises(RejectedBusy):
                router.submit([4, 5, 6], 4)
        status, toks, _ = router.submit([7, 8, 9], 4).result(timeout=300)
        assert status == "done"
        snap = slo.snapshot()
        cls = snap["classes"]["interactive"]
        assert cls["bad_by_objective"].get("availability") == 3
        assert cls["events"] == 4  # 3 rejects + 1 healthy done
    finally:
        router.stop()


def test_fleet_slow_window_trips_fast_burn_clean_run_silent(fleet_params):
    """Satellite: ``slow_window`` stretches every scheduler tick by
    slow_s, so the victim's TTFT is >= slow_s by construction — over a
    0.15s objective that one bad request out of one is burn 100x and
    fast_burn fires. The identical fleet with no injector stays silent."""
    bus = EventBus("")
    faults = ServingFaultInjector(
        "slow_window@req2:r0", slow_ticks=6, slow_s=0.3, bus=bus,
    )
    _, router = _slo_fleet(fleet_params, n=1, faults=faults, bus=bus)
    router.start()
    try:
        status, _, _ = router.submit([1, 2, 3], 4).result(timeout=300)
        assert status == "done"
        dec, slo, alerts = _attach_slo(bus, router, ttft_s=0.15)

        # Accepted submit #2 arms the slow window; its own first tick is
        # already slowed, so THIS request's ttft >= 0.3 > 0.15.
        status, toks, _ = router.submit([4, 5, 6], 4).result(timeout=300)
        assert status == "done" and len(toks) == 4
        snap = slo.snapshot()
        assert snap["latency"]["fleet"]["ttft_s"]["min"] >= 0.3
        fast = [a for a in alerts
                if a["rule"] == "fast_burn" and a["state"] == "firing"]
        assert len(fast) == 1
        assert fast[0]["slo_class"] == "interactive"
        # Alert -> decision lineage pinned: the paging alert's id shows
        # up exactly once in the decision ledger, under the same rule.
        decs = [r for r in dec.tail() if r["decision"] == "slo_alert"]
        mine = [d for d in decs if d["alert_id"] == fast[0]["alert_id"]]
        assert len(mine) == 1 and mine[0]["rule"] == "fast_burn"
    finally:
        router.stop()

    # Counterpart: same fleet, no faults, generous objective -> silence.
    bus2, router2 = _slo_fleet(fleet_params, n=1)
    router2.start()
    try:
        router2.submit([1, 2, 3], 4).result(timeout=300)
        dec2, slo2, alerts2 = _attach_slo(bus2, router2, ttft_s=60.0)
        for p in ([4, 5], [6, 7, 8], [9]):
            status, _, _ = router2.submit(p, 4).result(timeout=300)
            assert status == "done"
        snap = slo2.snapshot()
        assert alerts2 == []
        assert snap["alerts"]["active"] == []
        cls = snap["classes"]["interactive"]
        assert cls["events"] == 3 and cls["bad"] == 0
    finally:
        router2.stop()


def test_fleet_health_surface_and_gateway_endpoints(fleet_params):
    """Tentpole surface: router.fleet_health() aggregates per-replica
    health_pull gauges; slo_snapshot() folds it into the SLO body; the
    gateway serves both GET /slo and GET /metricsz over real HTTP."""
    bus, router = _slo_fleet(fleet_params, n=2)
    router.start()
    try:
        for p in ([1, 2, 3], [4, 5], [6, 7, 8, 9]):
            status, _, _ = router.submit(p, 4).result(timeout=300)
            assert status == "done"
        dec, slo, _ = _attach_slo(bus, router, ttft_s=60.0)

        fh = router.fleet_health()
        assert set(fh["replicas"]) == {"0", "1"}
        for snap_r in fh["replicas"].values():
            assert snap_r["fence"] == 0
            assert snap_r["gauges"]["rows_capacity"] == 2
        fleet = fh["fleet"]
        assert fleet["replicas_total"] == 2
        assert fleet["replicas_active"] == 2
        # Gauges are SUMS across replicas.
        assert fleet["gauges"]["rows_capacity"] == 4.0
        assert fleet["gauges"]["pool_total"] == sum(
            r["gauges"]["pool_total"] for r in fh["replicas"].values()
        ) > 0

        snap = router.slo_snapshot()
        assert snap["fleet_health"]["fleet"]["replicas_total"] == 2
        json.dumps(snap)  # wire-clean

        gw = ServingGateway(router, port=0, slo=slo).start()
        try:
            base = f"http://127.0.0.1:{gw.port}"
            with urllib.request.urlopen(base + "/slo", timeout=10) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
            # The router's slo_snapshot wins: fleet health included.
            assert body["fleet_health"]["fleet"]["replicas_total"] == 2
            assert body["alerts"]["active"] == []
            assert body["latency"]["fleet"]["e2e_s"]["count"] >= 0
            with urllib.request.urlopen(
                base + "/metricsz", timeout=10
            ) as resp:
                assert resp.status == 200
                mz = json.loads(resp.read())
            assert "gauges" in mz
        finally:
            gw.stop()
    finally:
        router.stop()


def test_build_live_report_reconciles_within_rank_bounds():
    """The --live reconciliation contract, unit-tested with EXACTLY the
    analyzer the CI gate runs: live sketch quantiles over a synthetic
    stream land inside the exact offline rank band; a perturbed snapshot
    is flagged as a problem."""
    spec = importlib.util.spec_from_file_location(
        "obs_report_live_unit", OBS_REPORT
    )
    obs = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs)

    rng = random.Random(9)
    t = [1000.0]
    bus = EventBus(clock=lambda: t[0], wall=lambda: t[0])
    slo = SLOEngine(
        classes=default_slo_classes(ttft_s=60.0, e2e_s=600.0),
        bus=bus, clock=lambda: t[0], window_s=3600.0,
    )
    events = []
    bus.subscribe(events.append)
    for i in range(300):
        t[0] += 0.01
        bus.emit(
            "req_done", replica=i % 3,
            ttft_s=rng.lognormvariate(-2.0, 0.8),
            tpot_s=rng.uniform(0.001, 0.02),
            e2e_s=rng.lognormvariate(0.0, 0.5),
            queue_wait_s=abs(rng.gauss(0.0, 0.1)),
        )
    snap = slo.snapshot()
    rep = obs.build_live_report(snap, events)
    assert rep["problems"] == []
    for m in obs.LIVE_METRICS:
        assert rep["reconcile"][m]["checked"], m
        assert rep["reconcile"][m]["offline_count"] == 300
    assert rep["alerts_active"] == []

    # Perturb one live quantile far outside the rank band: flagged.
    bad = json.loads(json.dumps(snap))
    bad["latency"]["fleet"]["ttft_s"]["p99"] *= 50.0
    rep_bad = obs.build_live_report(bad, events)
    assert any("ttft_s p99" in p for p in rep_bad["problems"])
