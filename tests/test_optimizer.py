"""In-repo AdamW vs optax reference; schedules; clipping."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pretraining_llm_tpu.config import TrainConfig
from pretraining_llm_tpu.training import optimizer as opt


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "blocks": {
            "mlp": {"w1": jax.random.normal(k1, (4, 8)), "b1": jnp.zeros((8,))},
        },
        "tok_embed": {"embedding": jax.random.normal(k2, (16, 4))},
        "final_norm": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }


def test_adamw_matches_optax():
    cfg = TrainConfig(lr=1e-3, weight_decay=0.1, adam_b1=0.9, adam_b2=0.95, adam_eps=1e-8)
    params = _params(jax.random.key(0))
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)

    mask = opt.decay_mask(params)
    ref_tx = optax.chain(
        optax.scale_by_adam(b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps),
        optax.add_decayed_weights(cfg.weight_decay, mask=mask),
        optax.scale(-cfg.lr),
    )
    ref_state = ref_tx.init(params)
    ours_state = opt.adamw_init(params)

    p_ref, p_ours = params, params
    for _ in range(5):
        updates, ref_state = ref_tx.update(grads, ref_state, p_ref)
        p_ref = optax.apply_updates(p_ref, updates)
        p_ours, ours_state = opt.adamw_update(grads, ours_state, p_ours, jnp.float32(cfg.lr), cfg)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        p_ref,
        p_ours,
    )


def test_decay_mask_excludes_biases_and_norms():
    params = _params(jax.random.key(0))
    mask = opt.decay_mask(params)
    assert mask["blocks"]["mlp"]["w1"] is True
    assert mask["blocks"]["mlp"]["b1"] is False
    assert mask["tok_embed"]["embedding"] is True
    assert mask["final_norm"]["scale"] is False
    assert mask["final_norm"]["bias"] is False


def test_decay_mask_covers_every_leaf_of_every_preset():
    """Every param leaf of every preset must be INTENTIONALLY classified.

    Guards the VERDICT r2 weak-#3 failure class: a new leaf name (e.g. the
    GQA ``wq``/``wkv`` projections) silently defaulting to no-decay because
    ``_DECAY_LEAVES`` didn't know it. Classification is by name (several bias
    leaves are >=2-D, so rank can't be the rule): every leaf must be in
    exactly one of ``_DECAY_LEAVES`` / ``_NO_DECAY_LEAVES``, and every
    weight-matrix leaf (w*, kernel, embedding, router) must be decayed.
    """
    import dataclasses

    from pretraining_llm_tpu import config as cfglib
    from pretraining_llm_tpu.models import transformer

    assert not (opt._DECAY_LEAVES & opt._NO_DECAY_LEAVES)

    seen_names = set()
    for preset in cfglib.list_presets():
        model = cfglib.get_preset(preset).model
        # Shrink to toy dims but keep every structural flag (GQA ratio, MoE,
        # activation, biases, tying) so the leaf-name set is the preset's own.
        tiny = dataclasses.replace(
            model,
            vocab_size=64,
            context_length=32,
            d_model=16,
            n_heads=4,
            n_layers=2,
            d_head=4,
            n_kv_heads=(2 if (model.n_kv_heads or model.n_heads) != model.n_heads else None),
            n_experts=min(model.n_experts, 4),
        )
        params = transformer.init_params(tiny, jax.random.key(0))
        mask = opt.decay_mask(params)
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_mask = jax.tree.leaves(mask)
        assert len(flat) == len(flat_mask)
        for (path, leaf), decayed in zip(flat, flat_mask):
            name = str(path[-1].key) if hasattr(path[-1], "key") else str(path[-1])
            seen_names.add(name)
            assert name in opt._DECAY_LEAVES or name in opt._NO_DECAY_LEAVES, (
                f"{preset}: unclassified param leaf {name!r} at "
                f"{jax.tree_util.keystr(path)} — add it to _DECAY_LEAVES or "
                f"_NO_DECAY_LEAVES in training/optimizer.py"
            )
            is_matrix = name.startswith("w") or name in {"kernel", "embedding", "router"}
            assert decayed == is_matrix, (
                f"{preset}: leaf {name!r} decayed={decayed}, expected {is_matrix}"
            )
    # The GQA leaves must actually appear in the sweep (llama3-1b-gqa preset),
    # otherwise this test silently lost its teeth.
    assert {"wq", "wkv"} <= seen_names


def test_clip_by_global_norm():
    grads = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
    clipped, norm = opt.clip_by_global_norm(grads, 1.0)
    np.testing.assert_allclose(float(norm), np.sqrt(3 * 16 + 4 * 9), rtol=1e-6)
    np.testing.assert_allclose(float(opt.global_norm(clipped)), 1.0, rtol=1e-4)
    # Under the limit: untouched
    same, _ = opt.clip_by_global_norm(grads, 100.0)
    np.testing.assert_allclose(np.asarray(same["a"]), np.asarray(grads["a"]), rtol=1e-6)


def test_lr_schedules():
    cfg = TrainConfig(lr=1e-3, train_steps=1000, warmup_frac=0.1, lr_schedule="warmup_constant")
    lrs = [float(opt.learning_rate(jnp.int32(s), cfg)) for s in [0, 50, 99, 100, 500, 999]]
    assert lrs[0] < lrs[1] < lrs[2] <= 1e-3 + 1e-9
    np.testing.assert_allclose(lrs[3:], 1e-3, rtol=1e-5)

    cfg = TrainConfig(lr=1e-3, train_steps=1000, warmup_frac=0.1, lr_schedule="warmup_cosine", min_lr_frac=0.1)
    mid = float(opt.learning_rate(jnp.int32(550), cfg))
    end = float(opt.learning_rate(jnp.int32(999), cfg))
    assert 1e-4 < mid < 1e-3
    np.testing.assert_allclose(end, 1e-4, rtol=0.05)
