"""In-repo AdamW vs optax reference; schedules; clipping."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pretraining_llm_tpu.config import TrainConfig
from pretraining_llm_tpu.training import optimizer as opt
from pretraining_llm_tpu.utils import jax_compat


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "blocks": {
            "mlp": {"w1": jax.random.normal(k1, (4, 8)), "b1": jnp.zeros((8,))},
        },
        "tok_embed": {"embedding": jax.random.normal(k2, (16, 4))},
        "final_norm": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }


def test_adamw_matches_optax():
    cfg = TrainConfig(lr=1e-3, weight_decay=0.1, adam_b1=0.9, adam_b2=0.95, adam_eps=1e-8)
    params = _params(jax.random.key(0))
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)

    mask = opt.decay_mask(params)
    ref_tx = optax.chain(
        optax.scale_by_adam(b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps),
        optax.add_decayed_weights(cfg.weight_decay, mask=mask),
        optax.scale(-cfg.lr),
    )
    ref_state = ref_tx.init(params)
    ours_state = opt.adamw_init(params)

    p_ref, p_ours = params, params
    for _ in range(5):
        updates, ref_state = ref_tx.update(grads, ref_state, p_ref)
        p_ref = optax.apply_updates(p_ref, updates)
        p_ours, ours_state = opt.adamw_update(grads, ours_state, p_ours, jnp.float32(cfg.lr), cfg)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        p_ref,
        p_ours,
    )


def test_decay_mask_excludes_biases_and_norms():
    params = _params(jax.random.key(0))
    mask = opt.decay_mask(params)
    assert mask["blocks"]["mlp"]["w1"] is True
    assert mask["blocks"]["mlp"]["b1"] is False
    assert mask["tok_embed"]["embedding"] is True
    assert mask["final_norm"]["scale"] is False
    assert mask["final_norm"]["bias"] is False


def test_decay_mask_covers_every_leaf_of_every_preset():
    """Every param leaf of every preset must be INTENTIONALLY classified.

    Guards the VERDICT r2 weak-#3 failure class: a new leaf name (e.g. the
    GQA ``wq``/``wkv`` projections) silently defaulting to no-decay because
    ``_DECAY_LEAVES`` didn't know it. Classification is by name (several bias
    leaves are >=2-D, so rank can't be the rule): every leaf must be in
    exactly one of ``_DECAY_LEAVES`` / ``_NO_DECAY_LEAVES``, and every
    weight-matrix leaf (w*, kernel, embedding, router) must be decayed.
    """
    import dataclasses

    from pretraining_llm_tpu import config as cfglib
    from pretraining_llm_tpu.models import transformer

    assert not (opt._DECAY_LEAVES & opt._NO_DECAY_LEAVES)

    seen_names = set()
    for preset in cfglib.list_presets():
        model = cfglib.get_preset(preset).model
        # Shrink to toy dims but keep every structural flag (GQA ratio, MoE,
        # activation, biases, tying) so the leaf-name set is the preset's own.
        tiny = dataclasses.replace(
            model,
            vocab_size=64,
            context_length=32,
            d_model=16,
            n_heads=4,
            n_layers=2,
            d_head=4,
            n_kv_heads=(2 if (model.n_kv_heads or model.n_heads) != model.n_heads else None),
            n_experts=min(model.n_experts, 4),
        )
        params = transformer.init_params(tiny, jax.random.key(0))
        mask = opt.decay_mask(params)
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_mask = jax.tree.leaves(mask)
        assert len(flat) == len(flat_mask)
        for (path, leaf), decayed in zip(flat, flat_mask):
            name = str(path[-1].key) if hasattr(path[-1], "key") else str(path[-1])
            seen_names.add(name)
            assert name in opt._DECAY_LEAVES or name in opt._NO_DECAY_LEAVES, (
                f"{preset}: unclassified param leaf {name!r} at "
                f"{jax.tree_util.keystr(path)} — add it to _DECAY_LEAVES or "
                f"_NO_DECAY_LEAVES in training/optimizer.py"
            )
            is_matrix = name.startswith("w") or name in {"kernel", "embedding", "router"}
            assert decayed == is_matrix, (
                f"{preset}: leaf {name!r} decayed={decayed}, expected {is_matrix}"
            )
    # The GQA leaves must actually appear in the sweep (llama3-1b-gqa preset),
    # otherwise this test silently lost its teeth.
    assert {"wq", "wkv"} <= seen_names


def test_clip_by_global_norm():
    grads = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
    clipped, norm = opt.clip_by_global_norm(grads, 1.0)
    np.testing.assert_allclose(float(norm), np.sqrt(3 * 16 + 4 * 9), rtol=1e-6)
    np.testing.assert_allclose(float(opt.global_norm(clipped)), 1.0, rtol=1e-4)
    # Under the limit: untouched
    same, _ = opt.clip_by_global_norm(grads, 100.0)
    np.testing.assert_allclose(np.asarray(same["a"]), np.asarray(grads["a"]), rtol=1e-6)


def test_lr_schedules():
    cfg = TrainConfig(lr=1e-3, train_steps=1000, warmup_frac=0.1, lr_schedule="warmup_constant")
    lrs = [float(opt.learning_rate(jnp.int32(s), cfg)) for s in [0, 50, 99, 100, 500, 999]]
    assert lrs[0] < lrs[1] < lrs[2] <= 1e-3 + 1e-9
    np.testing.assert_allclose(lrs[3:], 1e-3, rtol=1e-5)

    cfg = TrainConfig(lr=1e-3, train_steps=1000, warmup_frac=0.1, lr_schedule="warmup_cosine", min_lr_frac=0.1)
    mid = float(opt.learning_rate(jnp.int32(550), cfg))
    end = float(opt.learning_rate(jnp.int32(999), cfg))
    assert 1e-4 < mid < 1e-3
    np.testing.assert_allclose(end, 1e-4, rtol=0.05)


# ---------------------------------------------------------------------------
# Adafactor
# ---------------------------------------------------------------------------


def _tiny_adafactor_cfg(**train_kw):
    import dataclasses as dc

    from pretraining_llm_tpu.config import get_preset

    cfg = get_preset("tiny")
    return cfg.replace(
        train=dc.replace(cfg.train, optimizer="adafactor", **train_kw)
    )


def test_wsd_schedule_shape():
    """WSD: linear warmup -> flat at lr -> linear decay to min_lr over the
    final decay_frac of the run."""
    cfg = TrainConfig(lr=1.0, lr_schedule="warmup_stable_decay",
                      train_steps=1000, warmup_frac=0.1, decay_frac=0.2,
                      min_lr_frac=0.1)
    lr = lambda s: float(opt.learning_rate(jnp.asarray(s), cfg))
    assert lr(0) < 0.02                     # warmup start
    assert abs(lr(99) - 1.0) < 0.02         # warmup end
    assert lr(400) == 1.0 == lr(799)        # stable plateau
    assert 0.1 < lr(900) < 1.0              # mid-decay
    assert abs(lr(1000) - 0.1) < 1e-6       # floor
    # plateau really is flat (no cosine curvature)
    assert lr(500) == lr(700)
    # decay_frac ~ 1.0: decay start clamps to the warmup boundary — no LR
    # cliff at the handoff (continuous through the boundary).
    cfg_full = TrainConfig(lr=1.0, lr_schedule="warmup_stable_decay",
                           train_steps=1000, warmup_frac=0.1, decay_frac=1.0,
                           min_lr_frac=0.1)
    lrf = lambda s: float(opt.learning_rate(jnp.asarray(s), cfg_full))
    assert abs(lrf(100) - lrf(99)) < 0.02


def test_adafactor_state_shapes_and_size():
    """Factoring rule: >=3-D and top-level 2-D leaves are factored over the
    last two axes (leading axes kept — the interleave baking permutes axis
    0 of every blocks array); blocks 2-D leaves and vectors keep full v.
    Total state is a small fraction of params (the point of Adafactor)."""
    import jax

    from pretraining_llm_tpu.training import train_step as ts

    cfg = _tiny_adafactor_cfg()
    state = ts.init_train_state(cfg, jax.random.key(0))
    v = state["opt"]["v"]
    wqkv = state["params"]["blocks"]["attn"]["wqkv"]
    assert set(v["blocks"]["attn"]["wqkv"]) == {"r", "c"}
    assert v["blocks"]["attn"]["wqkv"]["r"].shape == wqkv.shape[:-1]
    assert v["blocks"]["attn"]["wqkv"]["c"].shape == wqkv.shape[:-2] + wqkv.shape[-1:]
    # stacked norm scale (L, d): full, keeps leading L
    assert set(v["blocks"]["ln1"]["scale"]) == {"full"}
    # top-level embedding (V, d): factored
    assert set(v["tok_embed"]["embedding"]) == {"r", "c"}
    pb = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(state["params"]))
    ob = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(state["opt"]))
    assert ob < 0.2 * pb, (ob, pb)


def test_adafactor_learns():
    import jax
    import jax.numpy as jnp

    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.training import train_step as ts

    cfg = _tiny_adafactor_cfg(lr=1e-2, batch_size=8)
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, None)
    it = loader.synthetic_iterator(
        cfg.model.vocab_size, cfg.model.context_length, 8, seed=0
    )
    first = last = None
    for i in range(30):
        x, y = next(it)
        state, m = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first - 0.5, (first, last)


@pytest.mark.skipif(
    not jax_compat._HAS_MODERN_SHARD_MAP,
    reason="interleaved pipeline step needs jax.shard_map (>=0.6); the "
    "legacy fallback lowers axis_index to PartitionId, rejected by XLA",
)
def test_adafactor_sharded_interleaved_pipeline_step():
    """Adafactor composes with the sharded state machinery: PP x TP x DP
    mesh, baked interleaved layout (the v tree's blocks arrays all carry
    the leading stacked-layer axis), replicated statistics pspec tree."""
    import dataclasses as dc

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.training import train_step as ts

    devs = np.asarray(jax.devices()).reshape(2, 1, 2, 1, 1, 2)
    mesh = Mesh(devs, ("data", "fsdp", "tensor", "seq", "expert", "pipe"))
    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dc.replace(
            tiny.model,
            n_layers=4, n_heads=4,
            pipeline_stages=2, pipeline_microbatches=2, pipeline_interleave=2,
            param_dtype="float32", compute_dtype="float32",
        ),
        mesh=dc.replace(tiny.mesh, data=2, tensor=2, pipe=2),
        train=dc.replace(
            tiny.train, optimizer="adafactor", batch_size=8, microbatches=1
        ),
    )
    x = jax.random.randint(
        jax.random.key(1), (8, cfg.model.context_length), 0, cfg.model.vocab_size
    )
    y = jnp.roll(x, -1, axis=1)
    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh, cfg)
    step = ts.build_train_step(cfg, mesh)
    sharded, metrics = step(sharded, (x, y))
    single = ts.build_train_step(cfg, mesh=None)
    state, metrics1 = single(state, (x, y))
    np.testing.assert_allclose(
        float(metrics["loss"]), float(metrics1["loss"]), rtol=1e-4
    )
    # second step exercises the updated (baked) v statistics
    sharded, metrics2 = step(sharded, (x, y))
    assert float(metrics2["loss"]) < float(metrics["loss"])


# ---------------------------------------------------------------------------
# Muon
# ---------------------------------------------------------------------------


def _tiny_muon_cfg(**train_kw):
    import dataclasses as dc

    from pretraining_llm_tpu.config import get_preset

    cfg = get_preset("tiny")
    return cfg.replace(train=dc.replace(cfg.train, optimizer="muon", **train_kw))


def test_newton_schulz_semi_orthogonalizes():
    """NS output's singular values land in the loose quintic band (~[0.6,
    1.3]) for random matrices, batched, both orientations."""
    for shape in ((3, 8, 16), (3, 16, 8), (1, 12, 12)):
        g = jax.random.normal(jax.random.key(1), shape)
        u = opt.newton_schulz_orthogonalize(g)
        assert u.shape == g.shape
        s = jnp.linalg.svd(u, compute_uv=False)
        assert float(s.min()) > 0.3, (shape, s)
        assert float(s.max()) < 1.6, (shape, s)


def test_muon_state_and_leaf_classification():
    """Hidden matrices carry momentum-only state; embeddings/head/vectors
    carry Adam mu+nu — every leaf in exactly one regime."""
    from pretraining_llm_tpu.training import train_step as ts

    cfg = _tiny_muon_cfg()
    state = ts.init_train_state(cfg, jax.random.key(0))
    s = state["opt"]["s"]
    assert set(s["blocks"]["attn"]["wqkv"]) == {"m"}
    assert set(s["blocks"]["mlp"]["w1"]) == {"m"}
    assert set(s["tok_embed"]["embedding"]) == {"mu", "nu"}
    assert set(s["blocks"]["ln1"]["scale"]) == {"mu", "nu"}
    # shapes mirror params
    assert (
        s["blocks"]["attn"]["wqkv"]["m"].shape
        == state["params"]["blocks"]["attn"]["wqkv"].shape
    )


def test_muon_update_rms_matched_and_orthogonal():
    """A Muon matrix update (pre-decay) reshapes the orthogonalized
    momentum: its 2-D view has RMS ~= 0.2 (the AdamW-matching rule) and
    near-isotropic spectrum."""
    cfg = TrainConfig(lr=1.0, weight_decay=0.0, optimizer="muon")
    params = {"blocks": {"mlp": {"w1": jnp.zeros((4, 8, 32))}}}
    grads = {"blocks": {"mlp": {"w1": jax.random.normal(jax.random.key(2), (4, 8, 32))}}}
    state = opt.muon_init(params)
    new_p, new_s = opt.muon_update(grads, state, params, jnp.float32(1.0), cfg)
    upd = -new_p["blocks"]["mlp"]["w1"]  # params were zero, lr=1
    # RMS match: scale 0.2*sqrt(32) on a semi-orthogonal (8,32) matrix
    # whose singular values ~1 -> RMS ~ 0.2*sqrt(32)*sqrt(8/ (8*32))... =
    # 0.2 * sqrt(max/min...)  — just assert the documented band loosely.
    rms = float(jnp.sqrt(jnp.mean(jnp.square(upd))))
    assert 0.1 < rms < 0.4, rms
    # momentum advanced
    assert float(jnp.abs(new_s["s"]["blocks"]["mlp"]["w1"]["m"]).max()) > 0


def test_muon_learns():
    import jax.numpy as jnp

    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.training import train_step as ts

    cfg = _tiny_muon_cfg(lr=3e-3, batch_size=8)
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, None)
    it = loader.synthetic_iterator(
        cfg.model.vocab_size, cfg.model.context_length, 8, seed=0
    )
    first = last = None
    for i in range(30):
        x, y = next(it)
        state, m = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first - 0.5, (first, last)


def test_muon_sharded_step_matches_single_device():
    """Muon composes with the sharded state machinery: FSDP x TP x DP mesh,
    momentum sharded exactly like its param (the {m} / {mu,nu} per-leaf
    pspec dicts), sharded step == single-device step."""
    import dataclasses as dc

    import numpy as np
    from jax.sharding import Mesh

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.training import train_step as ts

    devs = np.asarray(jax.devices()).reshape(2, 2, 2, 1, 1, 1)
    mesh = Mesh(devs, ("data", "fsdp", "tensor", "seq", "expert", "pipe"))
    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dc.replace(
            tiny.model, n_layers=2, n_heads=4,
            param_dtype="float32", compute_dtype="float32",
        ),
        mesh=dc.replace(tiny.mesh, data=2, fsdp=2, tensor=2),
        train=dc.replace(tiny.train, optimizer="muon", batch_size=8, microbatches=1),
    )
    x = jax.random.randint(
        jax.random.key(1), (8, cfg.model.context_length), 0, cfg.model.vocab_size
    )
    y = jnp.roll(x, -1, axis=1)
    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh, cfg)
    step = ts.build_train_step(cfg, mesh)
    sharded, metrics = step(sharded, (x, y))
    single = ts.build_train_step(cfg, mesh=None)
    state, metrics1 = single(state, (x, y))
    np.testing.assert_allclose(
        float(metrics["loss"]), float(metrics1["loss"]), rtol=1e-4
    )
    sharded, metrics2 = step(sharded, (x, y))
    assert float(metrics2["loss"]) < float(metrics["loss"])


def test_muon_matrix_view_moe_experts_batched_per_expert():
    """MoE expert stacks orthogonalize each expert's matrix independently:
    (L, E, D, F) views as L*E matrices of (D, F), never across experts."""
    from jax.tree_util import DictKey

    path = (DictKey("blocks"), DictKey("mlp"), DictKey("experts"), DictKey("w1"))
    assert opt._matrix_view(path, (4, 8, 64, 256)) == (32, 64, 256)
    # packed SwiGLU experts (L, E, D, 2, F): D -> 2F
    assert opt._matrix_view(path, (4, 8, 64, 2, 256)) == (32, 64, 512)
    path_w2 = path[:-1] + (DictKey("w2"),)
    assert opt._matrix_view(path_w2, (4, 8, 256, 64)) == (32, 256, 64)
    # dense (no experts in path): (L, D, F) -> L matrices of (D, F)
    dense = (DictKey("blocks"), DictKey("mlp"), DictKey("w1"))
    assert opt._matrix_view(dense, (4, 64, 256)) == (4, 64, 256)
    # attention wo contracts everything before its last axis
    wo = (DictKey("blocks"), DictKey("attn"), DictKey("wo"))
    assert opt._matrix_view(wo, (4, 8, 32, 256)) == (4, 256, 256)


def test_muon_learns_moe():
    """Muon trains an MoE config (per-expert orthogonalization path)."""
    import dataclasses as dc

    from pretraining_llm_tpu.config import get_preset
    from pretraining_llm_tpu.data import loader
    from pretraining_llm_tpu.training import train_step as ts

    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dc.replace(tiny.model, n_experts=4, experts_per_token=2),
        train=dc.replace(tiny.train, optimizer="muon", lr=3e-3, batch_size=8),
    )
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, None)
    it = loader.synthetic_iterator(
        cfg.model.vocab_size, cfg.model.context_length, 8, seed=0
    )
    first = last = None
    for i in range(20):
        x, y = next(it)
        state, m = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first - 0.3, (first, last)
