"""In-repo AdamW vs optax reference; schedules; clipping."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pretraining_llm_tpu.config import TrainConfig
from pretraining_llm_tpu.training import optimizer as opt


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "blocks": {
            "mlp": {"w1": jax.random.normal(k1, (4, 8)), "b1": jnp.zeros((8,))},
        },
        "tok_embed": {"embedding": jax.random.normal(k2, (16, 4))},
        "final_norm": {"scale": jnp.ones((4,)), "bias": jnp.zeros((4,))},
    }


def test_adamw_matches_optax():
    cfg = TrainConfig(lr=1e-3, weight_decay=0.1, adam_b1=0.9, adam_b2=0.95, adam_eps=1e-8)
    params = _params(jax.random.key(0))
    grads = jax.tree.map(lambda p: jnp.ones_like(p) * 0.1, params)

    mask = opt.decay_mask(params)
    ref_tx = optax.chain(
        optax.scale_by_adam(b1=cfg.adam_b1, b2=cfg.adam_b2, eps=cfg.adam_eps),
        optax.add_decayed_weights(cfg.weight_decay, mask=mask),
        optax.scale(-cfg.lr),
    )
    ref_state = ref_tx.init(params)
    ours_state = opt.adamw_init(params)

    p_ref, p_ours = params, params
    for _ in range(5):
        updates, ref_state = ref_tx.update(grads, ref_state, p_ref)
        p_ref = optax.apply_updates(p_ref, updates)
        p_ours, ours_state = opt.adamw_update(grads, ours_state, p_ours, jnp.float32(cfg.lr), cfg)

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        p_ref,
        p_ours,
    )


def test_decay_mask_excludes_biases_and_norms():
    params = _params(jax.random.key(0))
    mask = opt.decay_mask(params)
    assert mask["blocks"]["mlp"]["w1"] is True
    assert mask["blocks"]["mlp"]["b1"] is False
    assert mask["tok_embed"]["embedding"] is True
    assert mask["final_norm"]["scale"] is False
    assert mask["final_norm"]["bias"] is False


def test_clip_by_global_norm():
    grads = {"a": jnp.full((3,), 4.0), "b": jnp.full((4,), 3.0)}
    clipped, norm = opt.clip_by_global_norm(grads, 1.0)
    np.testing.assert_allclose(float(norm), np.sqrt(3 * 16 + 4 * 9), rtol=1e-6)
    np.testing.assert_allclose(float(opt.global_norm(clipped)), 1.0, rtol=1e-4)
    # Under the limit: untouched
    same, _ = opt.clip_by_global_norm(grads, 100.0)
    np.testing.assert_allclose(np.asarray(same["a"]), np.asarray(grads["a"]), rtol=1e-6)


def test_lr_schedules():
    cfg = TrainConfig(lr=1e-3, train_steps=1000, warmup_frac=0.1, lr_schedule="warmup_constant")
    lrs = [float(opt.learning_rate(jnp.int32(s), cfg)) for s in [0, 50, 99, 100, 500, 999]]
    assert lrs[0] < lrs[1] < lrs[2] <= 1e-3 + 1e-9
    np.testing.assert_allclose(lrs[3:], 1e-3, rtol=1e-5)

    cfg = TrainConfig(lr=1e-3, train_steps=1000, warmup_frac=0.1, lr_schedule="warmup_cosine", min_lr_frac=0.1)
    mid = float(opt.learning_rate(jnp.int32(550), cfg))
    end = float(opt.learning_rate(jnp.int32(999), cfg))
    assert 1e-4 < mid < 1e-3
    np.testing.assert_allclose(end, 1e-4, rtol=0.05)
