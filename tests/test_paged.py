"""Paged KV cache + continuous-batching serving engine.

Ground truth everywhere is the proven dense-cache path: greedy paged
serving must emit EXACTLY the tokens `generation.generate` (batch-1,
temperature 0) emits for the same prompt, regardless of admission order,
block fragmentation, preemption, int8 pools, or sliding windows.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation import paged
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    out = []
    for i in range(n):
        p = int(lengths[i % len(lengths)])
        out.append(rng.integers(0, CFG.vocab_size, size=p).tolist())
    return out


def _reference_greedy(params, cfg, prompt, n_new):
    """Batch-1 dense-cache greedy generation (the proven path)."""
    toks = generate(
        params, cfg, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


# -- allocator ------------------------------------------------------------


def test_allocator_invariants():
    a = paged.BlockAllocator(8)
    assert a.available == 7  # block 0 reserved
    got = a.alloc(3)
    assert got is not None and len(set(got)) == 3 and 0 not in got
    assert a.alloc(5) is None  # only 4 left: all-or-nothing
    assert a.available == 4
    a.free(got[:2])
    assert a.available == 6
    with pytest.raises(ValueError):
        a.free([got[0]])  # double free
    with pytest.raises(ValueError):
        paged.BlockAllocator(1)


def test_required_blocks():
    assert paged.required_blocks(1, 8) == 1
    assert paged.required_blocks(8, 8) == 1
    assert paged.required_blocks(9, 8) == 2


# -- forward-path contracts ----------------------------------------------


def test_forward_paged_validation(params):
    pools = transformer.make_paged_kv_pool(CFG, 4, 8, dtype="float32")
    tok = jnp.zeros((2, 1), jnp.int32)
    with pytest.raises(ValueError, match="paged=PagedInfo"):
        transformer.forward(params, tok, CFG, kv_cache=pools)
    info = transformer.PagedInfo(
        jnp.zeros((2, 8), jnp.int32), jnp.zeros((2,), jnp.int32)
    )
    dense = transformer.make_kv_cache(CFG, 2, 16, dtype="float32")
    with pytest.raises(ValueError, match="pool-layout"):
        transformer.forward(params, tok, CFG, kv_cache=dense, paged=info)


def test_pool_shape_and_reserved_block():
    # Default container is unstacked (per-layer pools, carry-aliasable).
    pools = transformer.make_paged_kv_pool(CFG, 6, 8)
    assert set(pools) == {"layers"} and len(pools["layers"]) == CFG.n_layers
    assert pools["layers"][0]["k_pool"].shape == (
        6, 8, CFG.kv_heads, CFG.head_dim
    )
    stacked = transformer.make_paged_kv_pool(
        dataclasses.replace(CFG, decode_cache_layout="stacked"), 6, 8
    )
    assert stacked["k_pool"].shape == (
        CFG.n_layers, 6, 8, CFG.kv_heads, CFG.head_dim
    )
    with pytest.raises(ValueError, match="multiple of 8"):
        transformer.make_paged_kv_pool(CFG, 6, 12)
    with pytest.raises(ValueError, match="n_blocks"):
        transformer.make_paged_kv_pool(CFG, 1, 8)


# -- engine == dense-cache greedy ----------------------------------------


def test_engine_matches_generate(params):
    prompts = _prompts(3)
    n_new = 10
    eng = ServingEngine(
        params, CFG, max_batch=3, n_blocks=32, block_size=8, temperature=0.0
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    assert eng.stats["preemptions"] == 0
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new), (
            f"request {rid} diverged from the dense-cache greedy path"
        )


def test_engine_more_requests_than_rows_fragmented(params):
    """6 requests through 2 rows: admission order + freed-block reuse give
    non-contiguous, reused block tables; outputs must be unaffected."""
    prompts = _prompts(6)
    n_new = 8
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=24, block_size=8, temperature=0.0
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    assert sorted(out) == sorted(rids)
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_engine_preemption_recovers_exactly(params):
    """A pool too small for both rows' full lengths forces preemption;
    recompute-on-resume greedy output must equal uninterrupted greedy."""
    prompts = [_prompts(1, lengths=(12,))[0], _prompts(1, lengths=(10,))[0]]
    n_new = 24
    # Each request needs ceil((12+24)/8)=5 blocks eventually; 7 usable
    # blocks cannot hold 5+5, so growth must preempt the younger row.
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=8, block_size=8, temperature=0.0
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    assert eng.stats["preemptions"] >= 1
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_engine_stop_token(params):
    p = _prompts(1)[0]
    n_new = 12
    ref = _reference_greedy(params, CFG, p, n_new)
    stop = ref[4]  # force an early stop on a token greedy WILL emit
    eng = ServingEngine(
        params, CFG, max_batch=1, n_blocks=16, block_size=8,
        temperature=0.0, stop_token=stop,
    )
    rid = eng.submit(p, n_new)
    out = eng.run()
    want = ref[: ref.index(stop)]
    assert out[rid] == want


def test_engine_int8_pool_matches_dense_int8(params):
    cfg8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
    prompts = _prompts(2)
    n_new = 8
    eng = ServingEngine(
        params, cfg8, max_batch=2, n_blocks=24, block_size=8, temperature=0.0
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, cfg8, p, n_new), (
            "paged int8 decode diverged from dense int8 decode"
        )


def test_engine_sliding_window(params):
    cfgw = dataclasses.replace(CFG, sliding_window=16)
    p = _prompts(1, lengths=(20,))[0]
    n_new = 10
    eng = ServingEngine(
        params, cfgw, max_batch=1, n_blocks=16, block_size=8, temperature=0.0
    )
    rid = eng.submit(p, n_new)
    out = eng.run()
    assert out[rid] == _reference_greedy(params, cfgw, p, n_new)


def test_engine_rejects_oversized(params):
    eng = ServingEngine(params, CFG, max_batch=1, n_blocks=4, block_size=8)
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(list(range(40)), CFG.context_length)
    with pytest.raises(ValueError, match="pool only has"):
        eng.submit(list(range(20)), 10)  # 30 tokens needs 4 blocks; 3 usable
    with pytest.raises(ValueError, match="empty"):
        eng.submit([], 4)


@pytest.mark.parametrize("window", [3, 8])
def test_engine_multistep_matches_generate(params, window):
    """steps_per_sched>1 runs K decode steps per device dispatch; greedy
    output must be unchanged, including rows finishing mid-window (their
    surplus tokens are discarded) and stop tokens."""
    prompts = _prompts(3)
    n_new = 10  # not a multiple of either window: mid-window finishes
    eng = ServingEngine(
        params, CFG, max_batch=3, n_blocks=32, block_size=8,
        temperature=0.0, steps_per_sched=window,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_engine_multistep_capacity_overshoot(params):
    """A row whose max_new ends exactly at pool/table capacity inside a
    multi-step window: the in-program scratch redirect must keep live
    blocks intact (other rows' outputs unchanged)."""
    # capacity = max_seq = 48 with block_size 24 on ctx-64 tiny.
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=8, block_size=24,
        temperature=0.0, steps_per_sched=8,
    )
    # 41+7 = 48 == capacity AND max_new(7) < window(8): the row's final
    # window step runs at seq == capacity, firing the in_range=False
    # scratch redirect (41+8 with an 8-aligned window would stop at
    # seq == capacity-1 and never exercise the guard).
    p_long = _prompts(1, lengths=(41,))[0]
    p_short = _prompts(1, lengths=(7,))[0]
    r1 = eng.submit(p_long, 7)
    r2 = eng.submit(p_short, 30)
    out = eng.run()
    assert out[r1] == _reference_greedy(params, CFG, p_long, 7)
    assert out[r2] == _reference_greedy(params, CFG, p_short, 30)


def test_engine_multistep_preemption(params):
    prompts = [_prompts(1, lengths=(12,))[0], _prompts(1, lengths=(10,))[0]]
    n_new = 24
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=8, block_size=8,
        temperature=0.0, steps_per_sched=4,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    assert eng.stats["preemptions"] >= 1
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_engine_block_size_not_dividing_context(params):
    """block_size that doesn't divide context_length: max_seq clamps to
    the aligned floor, so a near-context prompt is rejected at submit()
    instead of crashing prefill mid-serving (prefill pads to whole
    blocks, which would overflow the position tables)."""
    # tiny ctx=64; block_size=24 -> aligned max_seq=48
    eng = ServingEngine(params, CFG, max_batch=1, n_blocks=8, block_size=24)
    assert eng.max_seq == 48
    with pytest.raises(ValueError, match="exceeds"):
        eng.submit(list(range(45)), 10)  # fits ctx=64 but not aligned 48
    p = _prompts(1, lengths=(14,))[0]
    rid = eng.submit(p, 8)
    out = eng.run()
    assert out[rid] == _reference_greedy(params, CFG, p, 8)


def test_engine_sharded_matches_single_device(params, mesh8):
    """Paged serving over a dp x fsdp x tp mesh (params TP/FSDP-sharded,
    pool kv_heads sharded over 'tensor') == unsharded serving."""
    from pretraining_llm_tpu.generation.generate import shard_params_for_inference

    prompts = _prompts(2)
    n_new = 8
    sharded = shard_params_for_inference(params, mesh8)
    eng = ServingEngine(
        sharded, CFG, max_batch=2, n_blocks=24, block_size=8,
        temperature=0.0, steps_per_sched=4, mesh=mesh8,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_multitoken_paged_forward_matches_stepwise(params):
    """The multi-token paged forward (speculative verify) must produce,
    position by position, the same logits as T sequential single-token
    paged steps from the same pool state — and leave the pools in the
    same state."""
    rng = np.random.default_rng(3)
    prompts = _prompts(2)
    toks = [rng.integers(0, CFG.vocab_size, size=4).tolist() for _ in range(2)]
    bs = 8

    def build():
        pools = transformer.make_paged_kv_pool(CFG, 16, bs, dtype="float32")
        alloc = paged.BlockAllocator(16)
        tables = np.zeros((2, 4), np.int32)
        seq = np.zeros((2,), np.int32)
        for i, p in enumerate(prompts):
            need = paged.required_blocks(len(p) + 5, bs)
            ids = alloc.alloc(need)
            _, pools = paged.prefill_into_pool(
                params, CFG, pools, p, ids[: paged.required_blocks(len(p), bs)]
            )
            tables[i, : len(ids)] = ids
            seq[i] = len(p)
        return pools, tables, seq

    # A: one T=4 multi-token paged forward
    pools_a, tables, seq = build()
    tok_arr = jnp.asarray(np.stack([np.asarray(t) for t in toks]), jnp.int32)
    info = transformer.PagedInfo(jnp.asarray(tables), jnp.asarray(seq))
    logits_a, pools_a = transformer.forward(
        params, tok_arr, CFG, kv_cache=pools_a, paged=info
    )
    # B: 4 sequential single-token steps
    pools_b, tables_b, seq_b = build()
    logits_b = []
    for j in range(4):
        info_j = transformer.PagedInfo(
            jnp.asarray(tables_b), jnp.asarray(seq_b + j)
        )
        lj, pools_b = transformer.forward(
            params, tok_arr[:, j : j + 1], CFG, kv_cache=pools_b, paged=info_j
        )
        logits_b.append(np.asarray(lj[:, 0]))
    np.testing.assert_allclose(
        np.asarray(logits_a), np.stack(logits_b, axis=1), atol=2e-4
    )
    for leaf_a, leaf_b in zip(
        jax.tree.leaves(pools_a), jax.tree.leaves(pools_b)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf_a), np.asarray(leaf_b), atol=1e-5
        )


def test_batched_prefill_matches_sequential(params):
    """One fused prefill program for N prompts == N sequential prefills:
    same pool bytes on every real block (both layouts), same greedy
    first tokens."""
    prompts = _prompts(3)
    for layout in ("unstacked", "stacked"):
        cfg = dataclasses.replace(CFG, decode_cache_layout=layout)
        pools_a = transformer.make_paged_kv_pool(cfg, 16, 8, dtype="float32")
        pools_b = jax.tree.map(jnp.copy, pools_a)
        alloc = paged.BlockAllocator(16)
        ids = [alloc.alloc(paged.required_blocks(len(p), 8)) for p in prompts]
        lasts = []
        for p, b in zip(prompts, ids):
            last, pools_a = paged.prefill_into_pool(params, cfg, pools_a, p, b)
            lasts.append(int(np.argmax(np.asarray(last))))
        toks, pools_b = paged.prefill_into_pool_batched(
            params, cfg, pools_b, prompts, ids, jax.random.key(3),
            temperature=0.0,
        )
        assert np.asarray(toks).tolist() == lasts

        def k_block(pools, blk):
            if "layers" in pools:
                return np.stack(
                    [np.asarray(l["k_pool"][blk]) for l in pools["layers"]]
                )
            return np.asarray(pools["k_pool"][:, blk])

        for blk in sorted(set(b for row in ids for b in row)):
            np.testing.assert_allclose(
                k_block(pools_a, blk), k_block(pools_b, blk), atol=1e-6
            )


def test_batched_prefill_validation(params):
    pools = transformer.make_paged_kv_pool(CFG, 8, 8, dtype="float32")
    with pytest.raises(ValueError, match="no prompts"):
        paged.prefill_into_pool_batched(
            params, CFG, pools, [], [], jax.random.key(0)
        )
    with pytest.raises(ValueError, match="exactly"):
        paged.prefill_into_pool_batched(
            params, CFG, pools, [[1, 2, 3]], [[1, 2]], jax.random.key(0)
        )


@pytest.mark.parametrize("pipeline", [False, True])
def test_engine_pipeline_modes_match_generate(params, pipeline):
    """run(pipeline=...) must emit identical greedy outputs in both the
    synchronous and the double-buffered scheduler, through a gauntlet of
    more-requests-than-rows, mid-window finishes, and stop tokens."""
    prompts = _prompts(6)
    n_new = 9  # not a multiple of the window: mid-window finishes
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=24, block_size=8,
        temperature=0.0, steps_per_sched=4,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run(pipeline=pipeline)
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


@pytest.mark.parametrize("pipeline", [False, True])
def test_engine_pipeline_preemption_match(params, pipeline):
    """Tiny pool forcing preemption: the pipelined scheduler must flush
    its in-flight window before evicting, so recompute-on-resume resumes
    from the exact generated prefix in both modes."""
    prompts = [_prompts(1, lengths=(12,))[0], _prompts(1, lengths=(10,))[0]]
    n_new = 24
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=8, block_size=8,
        temperature=0.0, steps_per_sched=4,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run(pipeline=pipeline)
    assert eng.stats["preemptions"] >= 1
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


@pytest.mark.parametrize("pipeline", [False, True])
def test_engine_window_budget_clamp(params, pipeline):
    """A 32-step scheduling window with max_new=5 must CLAMP its decode
    windows to the rows' remaining-token budget (pow2-bucketed) instead
    of burning 32 lockstep steps per dispatch — outputs unchanged."""
    prompts = _prompts(2)
    n_new = 5
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=32, block_size=8,
        temperature=0.0, steps_per_sched=32,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run(pipeline=pipeline)
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)
    # 5 tokens/request: 1 from prefill + <= 8 window steps (pow2 bucket of
    # the 4 remaining), NOT 32+ — the clamp is the assertion.
    assert eng.stats["steps"] <= 16, eng.stats


def test_engine_pipelined_max_new_one(params):
    """max_new=1 requests finish on their deferred admission token alone;
    the row must free and be reusable without a dispatched window."""
    prompts = _prompts(3)
    eng = ServingEngine(
        params, CFG, max_batch=1, n_blocks=16, block_size=8,
        temperature=0.0, steps_per_sched=4,
    )
    rids = [eng.submit(p, 1) for p in prompts]
    out = eng.run(pipeline=True)
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, 1)


def test_paged_kernel_engine_matches_generate(params):
    """paged_attention_impl='kernel' (Pallas block-table kernel, interpret
    mode on CPU) must emit the same greedy tokens as the gather path's
    ground truth — through fragmentation, mid-window finishes, and block
    reuse."""
    cfgk = dataclasses.replace(CFG, paged_attention_impl="kernel")
    prompts = _prompts(4)
    n_new = 8
    eng = ServingEngine(
        params, cfgk, max_batch=2, n_blocks=24, block_size=8,
        temperature=0.0, steps_per_sched=4,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_paged_kernel_gqa_and_window(params):
    """Kernel path with GQA heads + sliding window == gather path, token
    for token."""
    from pretraining_llm_tpu.models.transformer import init_params

    cfg_g = dataclasses.replace(
        CFG, n_heads=4, n_kv_heads=2, sliding_window=16
    )
    params_g = init_params(cfg_g, jax.random.key(1))
    cfg_k = dataclasses.replace(cfg_g, paged_attention_impl="kernel")
    p = _prompts(1, lengths=(20,))[0]
    n_new = 10
    out = {}
    for name, cfg in (("gather", cfg_g), ("kernel", cfg_k)):
        eng = ServingEngine(
            params_g, cfg, max_batch=1, n_blocks=16, block_size=8,
            temperature=0.0,
        )
        rid = eng.submit(p, n_new)
        out[name] = eng.run()[rid]
    assert out["kernel"] == out["gather"]


def test_paged_kernel_config_validation():
    from pretraining_llm_tpu.config import ModelConfig

    with pytest.raises(ValueError, match="gather' or 'kernel"):
        ModelConfig(paged_attention_impl="magic")
    # kernel + int8 pools is a supported combination (the ragged kernel
    # fuses the scale-page dequant into its page loop) — must construct.
    cfg = ModelConfig(paged_attention_impl="kernel", kv_cache_dtype="int8")
    assert cfg.kv_cache_dtype == "int8"


DRAFT_CFG = dataclasses.replace(CFG, n_layers=1, d_model=16, n_heads=2)


@pytest.fixture(scope="module")
def draft_params():
    return transformer.init_params(DRAFT_CFG, jax.random.key(99))


def test_spec_serving_matches_generate(params, draft_params):
    """Speculative serving greedy output == dense-cache target-only greedy
    for ANY draft (here an untrained 1-layer model with a low hit rate):
    acceptance always verifies against the target argmax."""
    prompts = _prompts(4)
    n_new = 10
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=32, block_size=8,
        temperature=0.0, draft_params=draft_params, draft_cfg=DRAFT_CFG,
        spec_k=3,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    assert eng.stats["spec_rounds"] > 0
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_spec_serving_self_draft_accepts_everything(params):
    """Target-as-draft: fp32 greedy acceptance must be ~total, so each
    round emits k+1 tokens (the degenerate upper bound pins the
    accept/emit plumbing)."""
    p = _prompts(1)[0]
    n_new = 9
    eng = ServingEngine(
        params, CFG, max_batch=1, n_blocks=32, block_size=8,
        temperature=0.0, draft_params=params, draft_cfg=CFG, spec_k=2,
    )
    rid = eng.submit(p, n_new)
    out = eng.run()
    assert out[rid] == _reference_greedy(params, CFG, p, n_new)
    st = eng.stats
    assert st["spec_accepted"] == st["spec_proposed"], st


def test_spec_serving_preemption_and_stop(params, draft_params):
    """Spec serving through a pool small enough to force preemption, plus
    a stop token that lands mid-round: recompute-on-resume and surplus
    discard must both hold."""
    prompts = [_prompts(1, lengths=(12,))[0], _prompts(1, lengths=(10,))[0]]
    n_new = 16
    ref0 = _reference_greedy(params, CFG, prompts[0], n_new)
    stop = ref0[5]
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=8, block_size=8,
        temperature=0.0, stop_token=stop, draft_params=draft_params,
        draft_cfg=DRAFT_CFG, spec_k=3,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    for rid, p in zip(rids, prompts):
        ref = _reference_greedy(params, CFG, p, n_new)
        want = ref[: ref.index(stop)] if stop in ref else ref
        assert out[rid] == want, f"request {rid}"


def test_spec_serving_kernel_path_matches_generate(params, draft_params):
    """Speculative serving with paged_attention_impl='kernel': the draft
    steps run the single-token kernel, the verify runs the multi-token
    kernel — greedy output must still equal dense-cache target-only
    decoding."""
    cfgk = dataclasses.replace(CFG, paged_attention_impl="kernel")
    draft_k = dataclasses.replace(DRAFT_CFG, paged_attention_impl="kernel")
    prompts = _prompts(2)
    n_new = 8
    eng = ServingEngine(
        params, cfgk, max_batch=2, n_blocks=32, block_size=8,
        temperature=0.0, draft_params=draft_params, draft_cfg=draft_k,
        spec_k=3,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run()
    assert eng.stats["spec_rounds"] > 0
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_spec_serving_validation(params, draft_params):
    with pytest.raises(ValueError, match="all three"):
        ServingEngine(params, CFG, spec_k=2)
    with pytest.raises(ValueError, match="all three"):
        ServingEngine(params, CFG, draft_params=draft_params,
                      draft_cfg=DRAFT_CFG)
    bad = dataclasses.replace(DRAFT_CFG, vocab_size=128)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(params, CFG, draft_params=draft_params,
                      draft_cfg=bad, spec_k=2)
    with pytest.raises(ValueError, match="temperature-only"):
        ServingEngine(params, CFG, draft_params=draft_params,
                      draft_cfg=DRAFT_CFG, spec_k=2, top_k=5)


def test_engine_interleaved_submission(params):
    """Requests submitted WHILE others are decoding (the continuous part
    of continuous batching): mid-flight admission must not perturb
    already-running rows."""
    prompts = _prompts(4)
    n_new = 10
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=32, block_size=8, temperature=0.0
    )
    rids = [eng.submit(prompts[0], n_new), eng.submit(prompts[1], n_new)]
    for _ in range(3):
        eng.step()
    rids.append(eng.submit(prompts[2], n_new))
    for _ in range(2):
        eng.step()
    rids.append(eng.submit(prompts[3], n_new))
    out = eng.run()
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)
