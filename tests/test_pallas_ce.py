"""Fused CE head kernel vs the dense softmax-xent reference (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.ops.pallas_ce import fused_cross_entropy


def _dense_ce(h, w, labels):
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32))
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - gold


def _inputs(key, s=64, d=32, v=200, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    h = jax.random.normal(ks[0], (s, d), dtype)
    w = jax.random.normal(ks[1], (d, v), dtype) * 0.2
    labels = jax.random.randint(ks[2], (s,), 0, v)
    return h, w, labels


@pytest.mark.parametrize("v", [200, 256, 384])  # incl. non-multiple-of-128
def test_forward_matches_dense(v):
    h, w, labels = _inputs(jax.random.key(0), v=v)
    want = _dense_ce(h, w, labels)
    got = fused_cross_entropy(h, w, labels, block_s=16, block_v=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_gradients_match_dense():
    h, w, labels = _inputs(jax.random.key(1), s=32, d=16, v=160)

    def mean_dense(h, w):
        return jnp.mean(_dense_ce(h, w, labels))

    def mean_fused(h, w):
        return jnp.mean(
            fused_cross_entropy(h, w, labels, block_s=16, block_v=128, interpret=True)
        )

    g_dense = jax.grad(mean_dense, (0, 1))(h, w)
    g_fused = jax.jit(jax.grad(mean_fused, (0, 1)))(h, w)
    for a, b in zip(g_dense, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_nonuniform_cotangent():
    """Per-token cotangents (not just the mean) flow through the VJP —
    e.g. masked-loss or weighted-loss callers."""
    h, w, labels = _inputs(jax.random.key(2), s=32, d=16, v=160)
    weights = jnp.linspace(0.0, 2.0, 32)

    def weighted(fn):
        return lambda h, w: jnp.sum(fn(h, w) * weights)

    g_dense = jax.grad(weighted(lambda h, w: _dense_ce(h, w, labels)), (0, 1))(h, w)
    g_fused = jax.grad(
        weighted(
            lambda h, w: fused_cross_entropy(
                h, w, labels, block_s=16, block_v=128, interpret=True
            )
        ),
        (0, 1),
    )(h, w)
    for a, b in zip(g_dense, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_bf16_inputs():
    h, w, labels = _inputs(jax.random.key(3), dtype=jnp.bfloat16)
    want = _dense_ce(h, w, labels)
    got = fused_cross_entropy(h, w, labels, block_s=16, block_v=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2)


def test_bias_rejected():
    h, w, labels = _inputs(jax.random.key(4))
    with pytest.raises(ValueError, match="bias"):
        fused_cross_entropy(h, w, labels, bias=jnp.zeros(w.shape[1]), interpret=True)


def test_model_loss_fused_matches_chunked():
    """ce_impl='fused' through the whole model == the chunked head, for loss
    AND gradients (tiny shapes; the kernel runs in interpret mode on CPU)."""
    import dataclasses

    from pretraining_llm_tpu.config import ModelConfig
    from pretraining_llm_tpu.models import transformer

    cfg = ModelConfig(
        vocab_size=96, context_length=32, d_model=32, n_heads=4, n_layers=2,
        param_dtype="float32", compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    cfg_f = dataclasses.replace(cfg, ce_impl="fused")
    l_c, g_c = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg)
    l_f, g_f = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg_f)
    np.testing.assert_allclose(float(l_f), float(l_c), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_c, g_f,
    )


def test_model_fused_falls_back_for_biased_head():
    """lm_head_bias forces the chunked path (the kernel rejects bias)."""
    import dataclasses

    from pretraining_llm_tpu.config import ModelConfig
    from pretraining_llm_tpu.models import transformer

    cfg = ModelConfig(
        vocab_size=96, context_length=16, d_model=32, n_heads=4, n_layers=1,
        tie_embeddings=False, lm_head_bias=True, ce_impl="fused",
        param_dtype="float32", compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    with pytest.warns(UserWarning, match="fused.*degraded to chunked"):
        loss = transformer.loss_fn(params, tokens, jnp.roll(tokens, -1, 1), cfg)
    assert np.isfinite(float(loss))


def test_model_fused_degrade_warns_on_tensor_sharded_mesh():
    """VERDICT r2 #9: enabling the fused head on a TP mesh must SAY it
    degraded to chunked instead of silently training slower."""
    from jax.sharding import Mesh

    from pretraining_llm_tpu.config import ModelConfig
    from pretraining_llm_tpu.models import transformer
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    cfg = ModelConfig(
        vocab_size=96, context_length=16, d_model=32, n_heads=4, n_layers=1,
        ce_impl="fused", param_dtype="float32", compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    devs = np.asarray(jax.devices()).reshape(2, 1, 4, 1, 1, 1)
    mesh = Mesh(devs, ("data", "fsdp", "tensor", "seq", "expert", "pipe"))
    with activation_mesh(mesh):
        with pytest.warns(UserWarning, match="fused.*degraded to chunked"):
            loss = jax.jit(
                lambda p: transformer.loss_fn(p, tokens, jnp.roll(tokens, -1, 1), cfg)
            )(params)
    assert np.isfinite(float(loss))


def test_model_fused_on_data_sharded_mesh_matches_single_device():
    """Batch-sharded mesh: the fused head runs per-shard under shard_map (no
    global all-gather) and the loss+grads match the single-device run."""
    import dataclasses

    from jax.sharding import Mesh

    from pretraining_llm_tpu.config import ModelConfig
    from pretraining_llm_tpu.models import transformer
    from pretraining_llm_tpu.parallel.sharding import activation_mesh

    cfg = ModelConfig(
        vocab_size=96, context_length=32, d_model=32, n_heads=4, n_layers=2,
        ce_impl="fused", param_dtype="float32", compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    l_single, g_single = jax.value_and_grad(transformer.loss_fn)(
        params, tokens, targets, cfg
    )

    devs = np.asarray(jax.devices()).reshape(4, 2, 1, 1, 1, 1)
    mesh = Mesh(devs, ("data", "fsdp", "tensor", "seq", "expert", "pipe"))

    def sharded_loss(p):
        with activation_mesh(mesh):
            return transformer.loss_fn(p, tokens, targets, cfg)

    l_mesh, g_mesh = jax.jit(jax.value_and_grad(sharded_loss))(params)
    np.testing.assert_allclose(float(l_mesh), float(l_single), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_single, g_mesh,
    )


# ---------------------------------------------------------------------------
# The lse-saved chunked head (the default ce_impl="chunked" backward)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("with_bias", [False, True])
def test_lse_saved_chunked_matches_dense(with_bias):
    """_lse_saved_ce (custom VJP saving per-token lse) == whole-logits CE,
    loss AND all gradients, with and without an lm_head bias."""
    from pretraining_llm_tpu.models.transformer import _lse_saved_ce

    s, d, v, chunks = 64, 32, 160, 4
    h, w, labels = _inputs(jax.random.key(7), s=s, d=d, v=v)
    bias = (jax.random.normal(jax.random.key(8), (v,)) * 0.2) if with_bias else None

    def dense(h, w, bias):
        logits = h.astype(jnp.float32) @ w.astype(jnp.float32)
        if bias is not None:
            logits = logits + bias
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
        return jnp.sum(lse - gold)

    def chunked(h, w, bias):
        xs = h.reshape(chunks, s // chunks, d)
        ts = labels.reshape(chunks, s // chunks)
        return _lse_saved_ce(xs, w, bias, ts, jnp.float32)

    argnums = (0, 1, 2) if with_bias else (0, 1)
    l_ref, g_ref = jax.value_and_grad(dense, argnums=argnums)(h, w, bias)
    l_new, g_new = jax.value_and_grad(chunked, argnums=argnums)(h, w, bias)
    np.testing.assert_allclose(float(l_new), float(l_ref), rtol=1e-5)
    for a, b in zip(g_ref, g_new):
        np.testing.assert_allclose(
            np.asarray(b).reshape(np.asarray(a).shape), np.asarray(a),
            rtol=2e-4, atol=2e-5,
        )


# ---------------------------------------------------------------------------
# The dense saved-logits head (ce_impl="dense": zero backward recompute)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("with_bias", [False, True])
def test_dense_lse_ce_matches_reference(with_bias):
    """_dense_lse_ce (custom VJP saving compute-dtype logits + lse) ==
    whole-logits autodiff CE, loss AND all gradients. At compute dtype f32
    the saved logits are exact, so this pins the VJP math itself."""
    from pretraining_llm_tpu.models.transformer import _dense_lse_ce

    s, d, v = 64, 32, 160
    h, w, labels = _inputs(jax.random.key(11), s=s, d=d, v=v)
    bias = (jax.random.normal(jax.random.key(12), (v,)) * 0.2) if with_bias else None

    def ref(h, w, bias):
        logits = h.astype(jnp.float32) @ w.astype(jnp.float32)
        if bias is not None:
            logits = logits + bias
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
        return jnp.sum(lse - gold)

    def dense_head(h, w, bias):
        return _dense_lse_ce(h, w, bias, labels, jnp.float32)

    argnums = (0, 1, 2) if with_bias else (0, 1)
    l_ref, g_ref = jax.value_and_grad(ref, argnums=argnums)(h, w, bias)
    l_new, g_new = jax.value_and_grad(dense_head, argnums=argnums)(h, w, bias)
    np.testing.assert_allclose(float(l_new), float(l_ref), rtol=1e-5)
    for a, b in zip(g_ref, g_new):
        np.testing.assert_allclose(
            np.asarray(b).reshape(np.asarray(a).shape), np.asarray(a),
            rtol=2e-4, atol=2e-5,
        )


def test_model_loss_dense_matches_chunked():
    """ce_impl='dense' through the whole model == the chunked head, loss
    and gradients (fp32 compute: saved logits are exact)."""
    import dataclasses

    from pretraining_llm_tpu.config import ModelConfig
    from pretraining_llm_tpu.models import transformer

    cfg = ModelConfig(
        vocab_size=96, context_length=32, d_model=32, n_heads=4, n_layers=2,
        param_dtype="float32", compute_dtype="float32",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    cfg_d = dataclasses.replace(cfg, ce_impl="dense")
    l_c, g_c = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg)
    l_d, g_d = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg_d)
    np.testing.assert_allclose(float(l_d), float(l_c), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_c, g_d,
    )


def test_model_loss_dense_bf16_compute_close_to_chunked():
    """At bf16 compute the dense backward reads bf16-rounded saved logits
    where chunked recomputes f32-accum ones: grads agree to bf16 rounding."""
    import dataclasses

    from pretraining_llm_tpu.config import ModelConfig
    from pretraining_llm_tpu.models import transformer

    cfg = ModelConfig(
        vocab_size=96, context_length=32, d_model=32, n_heads=4, n_layers=2,
        param_dtype="float32", compute_dtype="bfloat16",
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    cfg_d = dataclasses.replace(cfg, ce_impl="dense")
    l_c, g_c = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg)
    l_d, g_d = jax.value_and_grad(transformer.loss_fn)(params, tokens, targets, cfg_d)
    # Forward loss is f32-accum logits both ways: tight.
    np.testing.assert_allclose(float(l_d), float(l_c), rtol=1e-5)
    # Gradients: bf16 logits rounding in the dense backward only.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-3
        ),
        g_c, g_d,
    )
