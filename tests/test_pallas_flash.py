"""Pallas flash attention kernels vs the naive path (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.ops.attention import naive_attention
from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention


def _qkv(key, b=2, t=64, h=2, dh=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, dh), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block_q,block_kv", [(16, 16), (32, 16), (16, 32), (64, 64)])
def test_forward_matches_naive(causal, block_q, block_kv):
    q, k, v = _qkv(jax.random.key(0))
    want = naive_attention(q, k, v, causal=causal)
    got = pallas_flash_attention(
        q, k, v, causal=causal, block_q=block_q, block_kv=block_kv, interpret=True
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_matches_naive(causal):
    q, k, v = _qkv(jax.random.key(1), t=32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_naive = jax.grad(loss(lambda q, k, v: naive_attention(q, k, v, causal=causal)), (0, 1, 2))(
        q, k, v
    )
    g_flash = jax.grad(
        loss(
            lambda q, k, v: pallas_flash_attention(
                q, k, v, causal=causal, block_q=16, block_kv=16, interpret=True
            )
        ),
        (0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_naive, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_uneven_block_shapes_fall_back_to_divisors():
    # t=48 is not divisible by the default 512 -> block sizes must self-adjust.
    q, k, v = _qkv(jax.random.key(2), t=48)
    want = naive_attention(q, k, v)
    got = pallas_flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_bf16_inputs():
    q, k, v = _qkv(jax.random.key(3), dtype=jnp.bfloat16)
    want = naive_attention(q, k, v)
    got = pallas_flash_attention(q, k, v, block_q=16, block_kv=16, interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=3e-2, atol=3e-2
    )


def test_long_sequence_memory_shape():
    # 1k tokens with small blocks: exercises many grid steps.
    q, k, v = _qkv(jax.random.key(4), b=1, t=1024, h=1, dh=8)
    got = pallas_flash_attention(q, k, v, block_q=128, block_kv=128, interpret=True)
    want = naive_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def _gqa_qkv(key, b=2, t=64, h=4, g=2, dh=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, t, h, dh), dtype)
    k = jax.random.normal(ks[1], (b, t, g, dh), dtype)
    v = jax.random.normal(ks[2], (b, t, g, dh), dtype)
    return q, k, v


@pytest.mark.parametrize("g", [1, 2])
def test_gqa_forward_matches_grouped_naive(g):
    """GQA through the kernel (no KV repeat) == the grouped naive einsum."""
    q, k, v = _gqa_qkv(jax.random.key(7), h=4, g=g)
    got = pallas_flash_attention(q, k, v, causal=True, block_q=16, block_kv=16, interpret=True)
    want = naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_gqa_backward_matches_grouped_naive():
    q, k, v = _gqa_qkv(jax.random.key(8), t=32, h=4, g=2)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            pallas_flash_attention(q, k, v, causal=True, block_q=16, block_kv=16, interpret=True) ** 2
        )

    g_naive = jax.grad(loss_naive, (0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    for a, b in zip(g_naive, g_flash):
        assert a.shape == b.shape  # dk/dv keep the G-head shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)


def test_gqa_bf16_forward():
    q, k, v = _gqa_qkv(jax.random.key(9), h=4, g=2, dtype=jnp.bfloat16)
    got = pallas_flash_attention(q, k, v, block_q=16, block_kv=16, interpret=True)
    want = naive_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize("g", [4, 2, 1])
def test_fused_single_block_backward_matches_naive(g):
    """t <= block triggers the fused dQ/dK/dV kernel (one pass, shared S/P)."""
    q, k, v = _gqa_qkv(jax.random.key(11), t=64, h=4, g=g)

    def loss_naive(q, k, v):
        return jnp.sum(naive_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        # default blocks (1024) >= t=64 -> nq == nk == 1 -> fused kernel
        return jnp.sum(pallas_flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    g_naive = jax.grad(loss_naive, (0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    for a, b in zip(g_naive, g_flash):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("policy_names", [("attn_o_res", "attn_lse"), ()])
def test_remat_saved_residuals_match_recompute(policy_names):
    """The 'save_qkv_attn'/'save_big' policies save the kernel's VJP residuals
    (o + squeezed lse, tagged in _flash_fwd) instead of re-running the forward
    in the backward. Gradients must be identical either way — this pins the
    tag names and the lse squeeze/re-expand pair in _flash_fwd/_bwd."""
    q, k, v = _qkv(jax.random.key(4), t=32)

    def loss(q, k, v):
        out = pallas_flash_attention(
            q, k, v, causal=True, block_q=16, block_kv=16, interpret=True
        )
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_plain = jax.grad(loss, (0, 1, 2))(q, k, v)
    ckpt = jax.checkpoint(
        loss, policy=jax.checkpoint_policies.save_only_these_names(*policy_names)
    )
    g_ckpt = jax.jit(jax.grad(ckpt, (0, 1, 2)))(q, k, v)
    for a, b in zip(g_plain, g_ckpt):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
