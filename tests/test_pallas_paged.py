"""Pallas paged-attention kernel vs the gather+masked-softmax reference.

The kernel (ops/pallas_paged.py) reads pool pages directly through the
scalar-prefetched block table; the reference materializes pool[tables]
and runs a masked softmax — the two must agree to accumulation-order
tolerance for every (GQA, window, dtype, fragmentation) combination.
Interpret mode on CPU (same convention as test_pallas_flash).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.ops.pallas_paged import paged_decode_attention


def _random_state(rng, b, n_blocks, max_blocks, bs):
    """Fragmented tables: each row owns a random disjoint set of pages."""
    perm = rng.permutation(np.arange(1, n_blocks)).tolist()
    tables = np.zeros((b, max_blocks), np.int32)
    seq = np.zeros((b,), np.int32)
    for i in range(b):
        n_pages = int(rng.integers(1, max_blocks + 1))
        own = [perm.pop() for _ in range(n_pages)]
        tables[i, : len(own)] = own
        seq[i] = int(rng.integers(0, n_pages * bs))
    return tables, seq


def _gather_ref_multi(q, kp, vp, tables, seq, window):
    """(B, T, H, D) reference: query t's frontier is seq + t. The single-
    token reference below is the T=1 slice of this — ONE source of truth
    for the mask/softmax numerics."""
    b, t, h, d = q.shape
    g = kp.shape[2]
    n_rep = h // g
    kv_len = tables.shape[1] * kp.shape[1]
    ck = jnp.repeat(kp[tables].reshape(b, kv_len, g, d), n_rep, axis=2)
    cv = jnp.repeat(vp[tables].reshape(b, kv_len, g, d), n_rep, axis=2)
    lin = jnp.arange(kv_len)
    pos = seq[:, None] + jnp.arange(t)[None, :]  # (B, T)
    mask = lin[None, None, :] <= pos[:, :, None]  # (B, T, kv_len)
    if window:
        mask = mask & (lin[None, None, :] > pos[:, :, None] - window)
    s = jnp.einsum(
        "bthd,bkhd->bthk", q.astype(jnp.float32), ck.astype(jnp.float32)
    ) / np.sqrt(d)
    s = jnp.where(mask[:, :, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bthk,bkhd->bthd", p, cv.astype(jnp.float32))


def _gather_ref(q, kp, vp, tables, seq, window):
    return _gather_ref_multi(q[:, None], kp, vp, tables, seq, window)[:, 0]


@pytest.mark.parametrize("g,window", [(8, 0), (2, 0), (4, 12), (1, 0)])
def test_kernel_matches_gather(g, window):
    rng = np.random.default_rng(g * 100 + window)
    b, h, d, bs, n_blocks, max_blocks = 3, 8, 64, 8, 24, 5
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    tables, seq = _random_state(rng, b, n_blocks, max_blocks, bs)
    out = paged_decode_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), window=window
    )
    ref = _gather_ref(q, kp, vp, tables, seq, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_kernel_bf16():
    rng = np.random.default_rng(7)
    b, h, g, d, bs, n_blocks, max_blocks = 2, 4, 2, 64, 8, 12, 3
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.bfloat16)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.bfloat16)
    tables, seq = _random_state(rng, b, n_blocks, max_blocks, bs)
    out = paged_decode_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq)
    )
    assert out.dtype == jnp.bfloat16
    ref = _gather_ref(q, kp, vp, tables, seq, 0)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_kernel_seq_zero_and_full():
    """Edge rows: seq 0 (only the just-written slot visible) and a row at
    its last slot."""
    rng = np.random.default_rng(11)
    b, h, g, d, bs, max_blocks = 2, 4, 4, 64, 8, 2
    q = jnp.asarray(rng.normal(size=(b, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(8, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(8, bs, g, d)), jnp.float32)
    tables = np.asarray([[3, 0], [5, 6]], np.int32)
    seq = np.asarray([0, 2 * bs - 1], np.int32)
    out = paged_decode_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq)
    )
    ref = _gather_ref(q, kp, vp, tables, seq, 0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("g,t,window", [(4, 5, 0), (2, 3, 0), (4, 4, 12)])
def test_kernel_multitoken_matches_gather(g, t, window):
    """The (B, T, H, D) form (speculative verify): per-query frontiers
    seq+t inside the kernel mask == the gather path's 3D mask."""
    rng = np.random.default_rng(g * 31 + t)
    b, h, d, bs, n_blocks, max_blocks = 2, 8, 64, 8, 24, 5
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    tables, seq = _random_state(rng, b, n_blocks, max_blocks, bs)
    # Keep every query's write slot within capacity (the engine's page
    # horizon guarantees this in real use).
    seq = np.minimum(seq, max_blocks * bs - t)
    out = paged_decode_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), window=window
    )
    assert out.shape == (b, t, h, d)
    ref = _gather_ref_multi(q, kp, vp, tables, seq, window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_kernel_validation():
    q = jnp.zeros((2, 4, 64))
    kp = jnp.zeros((8, 8, 3, 64))
    with pytest.raises(ValueError, match="divide"):
        paged_decode_attention(
            q, kp, kp, jnp.zeros((2, 2), jnp.int32), jnp.zeros((2,), jnp.int32)
        )
    kp = jnp.zeros((8, 8, 2, 64))
    with pytest.raises(ValueError, match="batch"):
        paged_decode_attention(
            q, kp, kp, jnp.zeros((3, 2), jnp.int32), jnp.zeros((3,), jnp.int32)
        )
