"""Ragged paged-attention kernel vs its XLA gather fallback.

The ragged kernel (ops/pallas_ragged.py) serves a batch whose rows carry
HETEROGENEOUS query counts — decode rows (q_len 1) and prefill-chunk
rows (q_len up to the chunk budget) in one launch — against the same
fragmented block pool as the uniform kernel. `ragged_gather_attention`
is the one source of truth for the mask/softmax numerics; the kernel
(interpret mode on CPU, same convention as test_pallas_paged) must agree
to accumulation-order tolerance for every (mix, GQA, window, dtype)
combination, including qlen=0 padding rows and pad-query zeroing.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.ops.pallas_ragged import (
    ragged_gather_attention,
    ragged_paged_attention,
)


def _random_state(rng, b, n_blocks, max_blocks, bs, t):
    """Fragmented tables + a ragged q_len per row: each row owns a random
    disjoint set of pages and a committed offset that leaves room for its
    own query count (the engine's page horizon guarantees this live)."""
    perm = rng.permutation(np.arange(1, n_blocks)).tolist()
    tables = np.zeros((b, max_blocks), np.int32)
    seq = np.zeros((b,), np.int32)
    qlens = np.zeros((b,), np.int32)
    for i in range(b):
        n_pages = int(rng.integers(1, max_blocks + 1))
        own = [perm.pop() for _ in range(n_pages)]
        tables[i, : len(own)] = own
        qlens[i] = int(rng.integers(1, t + 1))
        cap = n_pages * bs - int(qlens[i])
        seq[i] = int(rng.integers(0, max(cap, 0) + 1))
    return tables, seq, qlens


def _mixed_batch(rng, b, t, h, d, dtype=jnp.float32):
    """Half decode rows (q_len 1), half chunk rows (q_len up to t) — the
    launch shape chunked prefill actually produces."""
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), dtype)
    qlens = np.asarray(
        [1 if i % 2 == 0 else int(rng.integers(2, t + 1)) for i in range(b)],
        np.int32,
    )
    return q, qlens


@pytest.mark.parametrize("g,window", [(8, 0), (2, 0), (4, 12), (1, 0)])
def test_ragged_kernel_matches_gather(g, window):
    rng = np.random.default_rng(g * 100 + window)
    b, t, h, d, bs, n_blocks, max_blocks = 3, 6, 8, 64, 8, 24, 5
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    tables, seq, qlens = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    out = ragged_paged_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens),
        window=window,
    )
    assert out.shape == (b, t, h, d)
    ref = ragged_gather_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens),
        window=window,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("g", [4, 2])
def test_ragged_mixed_decode_and_chunk_rows(g):
    """The production mix: decode rows (q_len 1) share a launch with
    chunk rows; each row must get exactly the uniform-kernel answer it
    would get alone."""
    rng = np.random.default_rng(g)
    b, t, h, d, bs, n_blocks, max_blocks = 4, 8, 8, 64, 8, 32, 6
    q, qlens = _mixed_batch(rng, b, t, h, d)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    tables, seq, _ = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    seq = np.minimum(seq, max_blocks * bs - t)
    out = ragged_paged_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    )
    ref = ragged_gather_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # Per-row check against the gather ref evaluated for that row ALONE:
    # raggedness must not leak numerics across rows.
    for i in range(b):
        solo = ragged_gather_attention(
            q[i : i + 1], kp, vp, jnp.asarray(tables[i : i + 1]),
            jnp.asarray(seq[i : i + 1]), jnp.asarray(qlens[i : i + 1]),
        )
        np.testing.assert_allclose(
            np.asarray(out[i : i + 1]), np.asarray(solo), atol=2e-5
        )


def test_ragged_pad_queries_zero_and_qlen_zero_row():
    """Pad queries (t >= q_lens[b]) and fully-padded rows (q_len 0, the
    launch-width remainder) must come back exactly zero — the caller
    discards them, but NaNs would poison reductions downstream."""
    rng = np.random.default_rng(5)
    b, t, h, g, d, bs = 3, 4, 4, 2, 64, 8
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(8, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(8, bs, g, d)), jnp.float32)
    tables = np.asarray([[3, 0], [5, 6], [7, 0]], np.int32)
    seq = np.asarray([0, bs, 3], np.int32)
    qlens = np.asarray([2, 4, 0], np.int32)
    out = np.asarray(ragged_paged_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    ))
    ref = np.asarray(ragged_gather_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    ))
    np.testing.assert_allclose(out, ref, atol=2e-5)
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[0, 2:], 0.0)  # pad queries of row 0
    np.testing.assert_array_equal(out[2], 0.0)  # fully-padded row


def test_ragged_kernel_bf16():
    rng = np.random.default_rng(7)
    b, t, h, g, d, bs, n_blocks, max_blocks = 2, 5, 4, 2, 64, 8, 12, 3
    q, qlens = _mixed_batch(rng, b, t, h, d, jnp.bfloat16)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.bfloat16)
    tables, seq, _ = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    seq = np.minimum(seq, max_blocks * bs - t)
    out = ragged_paged_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    )
    assert out.dtype == jnp.bfloat16
    ref = ragged_gather_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


def _quantize_pool(x):
    """The engine's KV page convention (transformer._kv_quantize): int8
    codes with a per-(token, head) amax scale over the channel dim."""
    scale = np.maximum(np.abs(x).max(axis=-1, keepdims=True), 1e-8)
    q = np.asarray(
        jnp.round(jnp.asarray(x / scale * 127.0)), np.float32
    ).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(scale.astype(np.float32))


@pytest.mark.parametrize("scale_dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("g,window", [(8, 0), (2, 0), (4, 12), (1, 0)])
def test_ragged_kernel_int8_pool_grid(g, window, scale_dtype):
    """Quantized pools over the same identity grid as the exact-pool
    case: the kernel's fused in-loop dequant must match the gather
    reference (which dequantizes after assembly) to accumulation-order
    tolerance, and both must sit within quantization distance of the
    exact-pool answer. Covers both scale-pool dtypes the engine
    allocates (f32 legacy int8, bf16 int8-kv)."""
    rng = np.random.default_rng(1000 + g * 100 + window)
    b, t, h, d, bs, n_blocks, max_blocks = 3, 6, 8, 64, 8, 24, 5
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kf = rng.normal(size=(n_blocks, bs, g, d)).astype(np.float32)
    vf = rng.normal(size=(n_blocks, bs, g, d)).astype(np.float32)
    kq, ks = _quantize_pool(kf)
    vq, vs = _quantize_pool(vf)
    ks = ks.astype(scale_dtype)
    vs = vs.astype(scale_dtype)
    tables, seq, qlens = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    args = (jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens))
    out = ragged_paged_attention(
        q, kq, vq, *args, window=window, k_scale=ks, v_scale=vs
    )
    assert out.shape == (b, t, h, d)
    ref = ragged_gather_attention(
        q, kq, vq, *args, window=window, k_scale=ks, v_scale=vs
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # Same state through EXACT pools: the quantized answer must stay
    # within int8 noise of it (softmax-weighted ~1/127-scale values).
    exact = ragged_gather_attention(
        q, jnp.asarray(kf), jnp.asarray(vf), *args, window=window
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(exact), atol=0.08)


def test_ragged_int8_scale_validation():
    q = jnp.zeros((2, 3, 4, 64))
    kp = jnp.zeros((8, 8, 2, 64), jnp.int8)
    sc = jnp.ones((8, 8, 2, 1))
    tbl = jnp.zeros((2, 2), jnp.int32)
    seq = jnp.zeros((2,), jnp.int32)
    ql = jnp.ones((2,), jnp.int32)
    with pytest.raises(ValueError, match="k_scale"):
        ragged_paged_attention(q, kp, kp, tbl, seq, ql, k_scale=sc)
    with pytest.raises(ValueError, match="scale"):
        ragged_paged_attention(
            q, kp, kp, tbl, seq, ql,
            k_scale=jnp.ones((8, 8, 2)), v_scale=jnp.ones((8, 8, 2)),
        )


def test_ragged_matches_uniform_reference_on_uniform_batch():
    """With every q_len == t the ragged mask degenerates to the uniform
    multi-token mask — pin it against test_pallas_paged's reference math
    (inlined here) so the two kernels can never drift apart."""
    import jax

    rng = np.random.default_rng(13)
    b, t, h, g, d, bs, n_blocks, max_blocks = 2, 4, 8, 4, 64, 8, 24, 5
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    tables, seq, _ = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    seq = np.minimum(seq, max_blocks * bs - t)
    qlens = np.full((b,), t, np.int32)
    out = ragged_paged_attention(
        q, kp, vp, jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens)
    )
    kv_len = max_blocks * bs
    n_rep = h // g
    ck = jnp.repeat(kp[tables].reshape(b, kv_len, g, d), n_rep, axis=2)
    cv = jnp.repeat(vp[tables].reshape(b, kv_len, g, d), n_rep, axis=2)
    lin = jnp.arange(kv_len)
    pos = seq[:, None] + jnp.arange(t)[None, :]
    mask = lin[None, None, :] <= pos[:, :, None]
    s = jnp.einsum(
        "bthd,bkhd->bthk", q.astype(jnp.float32), ck.astype(jnp.float32)
    ) / np.sqrt(d)
    s = jnp.where(mask[:, :, None, :], s, -1e30)
    ref = jnp.einsum(
        "bthk,bkhd->bthd", jax.nn.softmax(s, axis=-1), cv.astype(jnp.float32)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_validation():
    q3 = jnp.zeros((2, 4, 64))
    kp = jnp.zeros((8, 8, 2, 64))
    ql = jnp.ones((2,), jnp.int32)
    with pytest.raises(ValueError, match="B, T, H, Dh"):
        ragged_paged_attention(
            q3, kp, kp, jnp.zeros((2, 2), jnp.int32),
            jnp.zeros((2,), jnp.int32), ql,
        )
    q = jnp.zeros((2, 3, 4, 64))
    with pytest.raises(ValueError, match="divide"):
        ragged_paged_attention(
            q, jnp.zeros((8, 8, 3, 64)), jnp.zeros((8, 8, 3, 64)),
            jnp.zeros((2, 2), jnp.int32), jnp.zeros((2,), jnp.int32), ql,
        )
    with pytest.raises(ValueError, match="batch"):
        ragged_paged_attention(
            q, kp, kp, jnp.zeros((3, 2), jnp.int32),
            jnp.zeros((3,), jnp.int32), jnp.ones((3,), jnp.int32),
        )
    with pytest.raises(ValueError, match="q_lens"):
        ragged_paged_attention(
            q, kp, kp, jnp.zeros((2, 2), jnp.int32),
            jnp.zeros((2,), jnp.int32), jnp.ones((3,), jnp.int32),
        )
    with pytest.raises(ValueError, match="mismatch"):
        ragged_paged_attention(
            q, kp, jnp.zeros((8, 8, 2, 32)), jnp.zeros((2, 2), jnp.int32),
            jnp.zeros((2,), jnp.int32), ql,
        )


# ---------------------------------------------------------------------------
# FA2 KV-split partitioning + AMLA add-based rescaling (the speed push).
# `ragged_gather_attention` stays the single source of truth: every variant
# below must reproduce it on the same fragmented state.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("amla", [False, True])
@pytest.mark.parametrize("kv_splits", [2, 3, 4, 0])  # 0 -> auto
@pytest.mark.parametrize("g,window", [(2, 0), (4, 12)])
def test_ragged_kv_split_amla_matches_gather(g, window, kv_splits, amla):
    """KV-split grid (every partition count incl. auto) x AMLA rescaling
    over the ragged identity grid. The split path computes per-partition
    unnormalized partials combined in XLA; AMLA replaces the MUL
    rescaling with exponent adds — both must land on the gather answer
    to accumulation-order tolerance."""
    rng = np.random.default_rng(7000 + g * 100 + window + kv_splits * 7 + amla)
    b, t, h, d, bs, n_blocks, max_blocks = 3, 6, 8, 64, 8, 24, 5
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_blocks, bs, g, d)), jnp.float32)
    tables, seq, qlens = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    args = (jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens))
    out = ragged_paged_attention(
        q, kp, vp, *args, window=window,
        kv_splits=kv_splits or None, amla=amla,
    )
    ref = ragged_gather_attention(q, kp, vp, *args, window=window)
    # AMLA's exp2 pipeline reorders the same flops; 2e-4 is ~500x the
    # measured worst case (3.6e-7) yet far below any masking error.
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4 if amla else 2e-5
    )


@pytest.mark.parametrize("qlen_kind", ["decode", "chunk"])
@pytest.mark.parametrize("int8", [False, True])
@pytest.mark.parametrize("window", [0, 12])
@pytest.mark.parametrize("seq_edge", [15, 16, 17])
def test_ragged_kv_split_partition_boundary(seq_edge, window, int8, qlen_kind):
    """seq_lens exactly on / one-below / one-above a KV-split edge.

    With nb=4 pages of bs=8 and kv_splits=2, partition 0 owns pages
    {0,1} (slots 0..15) and partition 1 owns pages {2,3}: slot 16 is the
    first slot of partition 1, so seq 15/16/17 put the last live token
    one-below / exactly-on / one-above the edge. The second partition is
    empty, one-token, or two-token — the l==0 guard and the cross-
    partition log-sum-exp combine must all hold, for decode (q_len 1)
    and chunk (q_len t) rows, windowed and int8 included."""
    rng = np.random.default_rng(9000 + seq_edge * 8 + window + int8 * 2)
    b, t, h, g, d, bs, n_blocks, max_blocks = 2, 4, 8, 2, 64, 8, 16, 4
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    kf = rng.normal(size=(n_blocks, bs, g, d)).astype(np.float32)
    vf = rng.normal(size=(n_blocks, bs, g, d)).astype(np.float32)
    tables = np.asarray([[3, 5, 7, 9], [2, 4, 6, 8]], np.int32)
    qlens = np.asarray([1, 1] if qlen_kind == "decode" else [t, t], np.int32)
    # seq is the committed length; the last live slot is seq + q_len - 1.
    seq = np.asarray([seq_edge - int(qlens[0]) + 1] * b, np.int32)
    scales = {}
    if int8:
        kq, ks = _quantize_pool(kf)
        vq, vs = _quantize_pool(vf)
        kp, vp, scales = kq, vq, {"k_scale": ks, "v_scale": vs}
    else:
        kp, vp = jnp.asarray(kf), jnp.asarray(vf)
    args = (jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens))
    ref = ragged_gather_attention(q, kp, vp, *args, window=window, **scales)
    for amla in (False, True):
        out = ragged_paged_attention(
            q, kp, vp, *args, window=window, kv_splits=2, amla=amla,
            **scales,
        )
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-4 if amla else 2e-5,
            err_msg=f"amla={amla}",
        )


def test_ragged_kv_split_int8_mixed_rows():
    """The full production mix under the split kernel: quantized pools,
    ragged decode+chunk rows, fragmented tables, splits x amla."""
    rng = np.random.default_rng(31)
    b, t, h, g, d, bs, n_blocks, max_blocks = 4, 8, 8, 4, 64, 8, 32, 6
    q, qlens = _mixed_batch(rng, b, t, h, d)
    kf = rng.normal(size=(n_blocks, bs, g, d)).astype(np.float32)
    vf = rng.normal(size=(n_blocks, bs, g, d)).astype(np.float32)
    kq, ks = _quantize_pool(kf)
    vq, vs = _quantize_pool(vf)
    tables, seq, _ = _random_state(rng, b, n_blocks, max_blocks, bs, t)
    seq = np.minimum(seq, max_blocks * bs - t)
    args = (jnp.asarray(tables), jnp.asarray(seq), jnp.asarray(qlens))
    ref = ragged_gather_attention(q, kq, vq, *args, k_scale=ks, v_scale=vs)
    for kv_splits, amla in [(2, False), (3, True), (None, True)]:
        out = ragged_paged_attention(
            q, kq, vq, *args, k_scale=ks, v_scale=vs,
            kv_splits=kv_splits, amla=amla,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref),
            atol=2e-4 if amla else 2e-5,
            err_msg=f"kv_splits={kv_splits} amla={amla}",
        )


def test_auto_kv_splits_heuristic():
    """The partition-count heuristic: more splits when the batch is too
    small to fill the grid, none when the batch already does; never
    leaves a partition with fewer than 2 pages; always >= 1."""
    from pretraining_llm_tpu.ops.pallas_ragged import _auto_kv_splits

    assert _auto_kv_splits(32, 1) == 8
    assert _auto_kv_splits(8, 1) == 4
    assert _auto_kv_splits(8, 2) == 4
    assert _auto_kv_splits(4, 2) == 2
    assert _auto_kv_splits(2, 1) == 1   # split would leave <2 pages each
    assert _auto_kv_splits(1, 1) == 1
    for nb in range(1, 40):
        for b in range(1, 12):
            p = _auto_kv_splits(nb, b)
            assert p >= 1
            assert p == 1 or nb // p >= 2
    assert _auto_kv_splits(64, 8) == 1  # batch fills the grid already


def test_ragged_kv_splits_validation():
    q = jnp.zeros((2, 3, 4, 64))
    kp = jnp.zeros((8, 8, 2, 64))
    tbl = jnp.zeros((2, 2), jnp.int32)
    seq = jnp.zeros((2,), jnp.int32)
    ql = jnp.ones((2,), jnp.int32)
    with pytest.raises(ValueError, match="kv_splits"):
        ragged_paged_attention(q, kp, kp, tbl, seq, ql, kv_splits=-1)
    # More splits than pages clamps rather than launching empty programs.
    out = ragged_paged_attention(q, kp, kp, tbl, seq, ql, kv_splits=64)
    assert out.shape == q.shape
