"""Pipeline parallelism: GPipe schedule over the 'pipe' mesh axis.

The decisive check is equivalence: the pipelined forward/train step must give
the same loss and gradients as the plain scanned model — the pipeline is a
schedule, not a different computation.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.parallel.sharding import activation_mesh
from pretraining_llm_tpu.training import train_step as ts
from pretraining_llm_tpu.utils import jax_compat

# Running a pipelined computation needs jax.shard_map: the legacy
# jax.experimental fallback lowers axis_index in a partial-manual region to
# PartitionId, which XLA's SPMD partitioner rejects as UNIMPLEMENTED.
# Validation/schedule tests don't execute the pipeline and still run.
requires_modern_shard_map = pytest.mark.skipif(
    not jax_compat._HAS_MODERN_SHARD_MAP,
    reason="pipelined execution needs jax.shard_map (>=0.6); legacy fallback "
    "lowers axis_index to PartitionId, rejected by SPMD partitioning",
)


@pytest.fixture(scope="module")
def mesh_pipe4() -> Mesh:
    devs = np.asarray(jax.devices()).reshape(2, 1, 1, 1, 1, 4)
    return Mesh(devs, ("data", "fsdp", "tensor", "seq", "expert", "pipe"))


def _cfg(**kw):
    base = dict(
        vocab_size=97,
        context_length=32,
        d_model=32,
        n_heads=4,
        n_layers=4,
        pipeline_stages=4,
        pipeline_microbatches=2,
        param_dtype="float32",
        compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


def test_pipeline_validation():
    with pytest.raises(ValueError):
        ModelConfig(n_layers=4, pipeline_stages=3)
    with pytest.raises(ValueError):
        ModelConfig(n_layers=4, pipeline_stages=2, attention_impl="ring")
    with pytest.raises(ValueError):
        ModelConfig(n_layers=4, pipeline_stages=2, sequence_parallel=True)


def test_pipeline_rejects_indivisible_local_batch(mesh_pipe4):
    """B=4 over 2 data shards -> local batch 2, not divisible by 4 micro."""
    cfg = _cfg(pipeline_microbatches=4)
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.context_length), 0, cfg.vocab_size)
    with pytest.raises(ValueError, match="pipeline_microbatches"):
        with activation_mesh(mesh_pipe4):
            transformer.forward(params, tokens, cfg)


@requires_modern_shard_map
def test_pipeline_forward_matches_scan(mesh_pipe4):
    """Pipelined forward == plain scanned forward (same params, same batch)."""
    cfg = _cfg()
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, cfg.context_length), 0, cfg.vocab_size)

    logits_ref, _ = jax.jit(
        lambda p, t: transformer.forward(p, t, cfg)
    )(params, tokens)

    def piped(p, t):
        with activation_mesh(mesh_pipe4):
            return transformer.forward(p, t, cfg)

    logits_pipe, _ = jax.jit(piped)(params, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(logits_ref), rtol=1e-4, atol=1e-4
    )


@requires_modern_shard_map
def test_pipeline_grads_match_scan(mesh_pipe4):
    cfg = _cfg()
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.context_length), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    g_ref = jax.jit(jax.grad(lambda p: transformer.loss_fn(p, tokens, targets, cfg)))(params)

    def piped_loss(p):
        with activation_mesh(mesh_pipe4):
            return transformer.loss_fn(p, tokens, targets, cfg)

    g_pipe = jax.jit(jax.grad(piped_loss))(params)
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_pipe = dict(
        (jax.tree_util.keystr(p), l) for p, l in jax.tree_util.tree_leaves_with_path(g_pipe)
    )
    for path, leaf in flat_ref:
        key = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(flat_pipe[key]), np.asarray(leaf), rtol=2e-3, atol=1e-5,
            err_msg=f"grad mismatch at {key}",
        )


@requires_modern_shard_map
def test_pipeline_train_step_runs_and_matches(mesh_pipe4):
    """Full sharded train step under 2-data x 4-pipe == single-device step."""
    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dataclasses.replace(
            tiny.model,
            n_layers=4,
            pipeline_stages=4,
            pipeline_microbatches=2,
            param_dtype="float32",
            compute_dtype="float32",
        ),
        mesh=dataclasses.replace(tiny.mesh, data=2, pipe=4),
        train=dataclasses.replace(tiny.train, batch_size=8, microbatches=1),
    )
    x = jax.random.randint(jax.random.key(1), (8, cfg.model.context_length), 0,
                           cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)

    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh_pipe4, cfg)
    step = ts.build_train_step(cfg, mesh_pipe4)
    sharded, metrics = step(sharded, (x, y))
    pipe_loss = float(metrics["loss"])

    single = ts.build_train_step(cfg, mesh=None)
    state, metrics1 = single(state, (x, y))
    np.testing.assert_allclose(pipe_loss, float(metrics1["loss"]), rtol=1e-4)
    assert int(jax.device_get(sharded["step"])) == 1


@requires_modern_shard_map
def test_pipeline_with_moe_aux(mesh_pipe4):
    """PP composes with MoE: aux loss flows out of the manual region."""
    cfg = _cfg(n_experts=2, experts_per_token=1, expert_capacity_factor=4.0)
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.context_length), 0, cfg.vocab_size)

    def piped(p, t):
        with activation_mesh(mesh_pipe4):
            return transformer.forward(p, t, cfg, return_aux=True)

    logits, _, aux = jax.jit(piped)(params, tokens)
    ref = jax.jit(lambda p, t: transformer.forward(p, t, cfg, return_aux=True))
    ref_logits, _, _ = ref(params, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4)
    # Pipeline aux = mean over GLOBAL microbatches (contiguous row blocks:
    # B=4 over 2 microbatches -> rows (0,1) and (2,3)) — the same grouping
    # the non-pipelined loss sees per microbatch.
    per_mb = [float(ref(params, tokens[i : i + 2])[2]) for i in (0, 2)]
    np.testing.assert_allclose(float(aux), np.mean(per_mb), rtol=1e-4)


def test_schedule_is_minimal_gpipe_and_bubble_shrinks_with_microbatches():
    """The tick loop runs exactly n_micro + n_stages - 1 iterations (no dead
    ticks), so bubble fraction is the GPipe/1F1B minimum for the microbatch
    count and decays toward 0 as microbatches grow."""
    from pretraining_llm_tpu.parallel.pipeline import bubble_fraction, schedule_ticks

    assert schedule_ticks(n_micro=4, n_stages=2) == 5
    assert schedule_ticks(n_micro=1, n_stages=1) == 1
    assert bubble_fraction(4, 2) == 1 / 5
    assert bubble_fraction(32, 2) == 1 / 33
    assert bubble_fraction(8, 4) < bubble_fraction(4, 4) < bubble_fraction(2, 4)


@pytest.mark.parametrize("interleave,n_layers", [(2, 8), (2, 16), (4, 16)])
@requires_modern_shard_map
def test_interleaved_pipeline_matches_scan(mesh_pipe4, interleave, n_layers):
    """Interleaved virtual stages are a schedule, not a different computation:
    forward and gradients must match the plain scanned model. 4 stages x V
    chunks; microbatches >= stages per the feasibility rule."""
    cfg = _cfg(
        n_layers=n_layers, pipeline_microbatches=4, pipeline_interleave=interleave
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, cfg.context_length), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    ref_logits, _ = jax.jit(lambda p, t: transformer.forward(p, t, cfg))(params, tokens)
    g_ref = jax.jit(jax.grad(lambda p: transformer.loss_fn(p, tokens, targets, cfg)))(params)

    def piped(p, t):
        with activation_mesh(mesh_pipe4):
            return transformer.forward(p, t, cfg)

    logits_pipe, _ = jax.jit(piped)(params, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_pipe), np.asarray(ref_logits), rtol=1e-4, atol=1e-4
    )

    def piped_loss(p):
        with activation_mesh(mesh_pipe4):
            return transformer.loss_fn(p, tokens, targets, cfg)

    g_pipe = jax.jit(jax.grad(piped_loss))(params)
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_pipe = dict(
        (jax.tree_util.keystr(p), l) for p, l in jax.tree_util.tree_leaves_with_path(g_pipe)
    )
    for path, leaf in flat_ref:
        key = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            np.asarray(flat_pipe[key]), np.asarray(leaf), rtol=2e-3, atol=1e-5,
            err_msg=f"grad mismatch at {key}",
        )


def test_interleave_validation():
    with pytest.raises(ValueError, match="pipeline_interleave"):
        ModelConfig(n_layers=4, pipeline_stages=2, pipeline_interleave=3)
    with pytest.raises(ValueError, match="pipeline_microbatches >= "):
        ModelConfig(
            n_layers=8, pipeline_stages=4, pipeline_interleave=2,
            pipeline_microbatches=2,
        )


def test_interleave_shrinks_bubble():
    from pretraining_llm_tpu.parallel.pipeline import bubble_fraction, schedule_ticks

    assert schedule_ticks(n_micro=4, n_stages=4, interleave=2) == 11
    # V-fold smaller fill/drain cost: (S-1)/(V*m + S-1).
    assert bubble_fraction(4, 4, interleave=2) == 3 / 11
    assert (
        bubble_fraction(4, 4, interleave=4)
        < bubble_fraction(4, 4, interleave=2)
        < bubble_fraction(4, 4)
    )


def test_interleave_requires_stages():
    with pytest.raises(ValueError, match="pipeline_stages > 1"):
        ModelConfig(n_layers=4, pipeline_stages=1, pipeline_interleave=2)


@pytest.fixture(scope="module")
def mesh_pp_tp() -> Mesh:
    devs = np.asarray(jax.devices()).reshape(2, 1, 2, 1, 1, 2)
    return Mesh(devs, ("data", "fsdp", "tensor", "seq", "expert", "pipe"))


@requires_modern_shard_map
def test_pipeline_composes_with_tensor_parallel(mesh_pp_tp):
    """PP x TP x DP: the pipe region is manual over 'pipe' only, so stage
    weights keep their tensor specs (GSPMD inserts the TP collectives inside
    each stage) and the step matches the single-device run."""
    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dataclasses.replace(
            tiny.model,
            n_layers=4,
            n_heads=4,
            pipeline_stages=2,
            pipeline_microbatches=2,
            pipeline_interleave=2,
            param_dtype="float32",
            compute_dtype="float32",
        ),
        mesh=dataclasses.replace(tiny.mesh, data=2, tensor=2, pipe=2),
        train=dataclasses.replace(tiny.train, batch_size=8, microbatches=1),
    )
    x = jax.random.randint(jax.random.key(1), (8, cfg.model.context_length), 0,
                           cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)

    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh_pp_tp, cfg)
    # TP really shards the stage weights: wqkv (L, D, 3, H, Dh) splits over
    # pipe on dim 0 AND tensor on dim 3.
    wqkv = sharded["params"]["blocks"]["attn"]["wqkv"]
    L, D = cfg.model.n_layers, cfg.model.d_model
    shard_shape = wqkv.sharding.shard_shape(wqkv.shape)
    assert shard_shape[0] == L // 2, shard_shape
    assert shard_shape[3] == cfg.model.n_heads // 2, shard_shape

    step = ts.build_train_step(cfg, mesh_pp_tp)
    sharded, metrics = step(sharded, (x, y))
    pipe_loss = float(metrics["loss"])

    single = ts.build_train_step(cfg, mesh=None)
    state, metrics1 = single(state, (x, y))
    np.testing.assert_allclose(pipe_loss, float(metrics1["loss"]), rtol=1e-4)


@pytest.mark.parametrize("axis", ["fsdp", "expert"])
@requires_modern_shard_map
def test_pipeline_composes_with_fsdp_and_ep(axis):
    """PP x FSDP and PP x EP: stage weights keep their fsdp/expert specs
    under the partial-manual pipe region and match single-device."""
    tiny = get_preset("tiny")
    model_kw = dict(
        n_layers=4,
        pipeline_stages=2,
        pipeline_microbatches=2,
        param_dtype="float32",
        compute_dtype="float32",
    )
    if axis == "expert":
        model_kw.update(n_experts=2, experts_per_token=1, expert_capacity_factor=4.0)
    cfg = tiny.replace(
        model=dataclasses.replace(tiny.model, **model_kw),
        mesh=dataclasses.replace(tiny.mesh, data=2, pipe=2, **{axis: 2}),
        train=dataclasses.replace(tiny.train, batch_size=8, microbatches=1),
    )
    shape = [1] * 6
    names = ("data", "fsdp", "tensor", "seq", "expert", "pipe")
    for name, size in (("data", 2), (axis, 2), ("pipe", 2)):
        shape[names.index(name)] = size
    mesh = Mesh(np.asarray(jax.devices()).reshape(shape), names)

    x = jax.random.randint(jax.random.key(1), (8, cfg.model.context_length), 0,
                           cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)
    state = ts.init_train_state(cfg, jax.random.key(0))
    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh, cfg)
    # The composed spec really shards stage weights (not just loss parity):
    # pipe splits the stacked layer dim AND the fsdp/expert dim splits too.
    if axis == "fsdp":
        w = sharded["params"]["blocks"]["attn"]["wqkv"]  # (L, D, 3, H, Dh)
        ss = w.sharding.shard_shape(w.shape)
        assert ss[0] == cfg.model.n_layers // 2 and ss[1] == cfg.model.d_model // 2, ss
    else:
        w = sharded["params"]["blocks"]["mlp"]["experts"]["w1"]  # (L, E, D, F)
        ss = w.sharding.shard_shape(w.shape)
        assert ss[0] == cfg.model.n_layers // 2 and ss[1] == 1, ss
    step = ts.build_train_step(cfg, mesh)
    sharded, metrics = step(sharded, (x, y))

    single = ts.build_train_step(cfg, mesh=None)
    state, metrics1 = single(state, (x, y))
    np.testing.assert_allclose(
        float(metrics["loss"]), float(metrics1["loss"]), rtol=1e-4
    )


@requires_modern_shard_map
def test_baked_layout_roundtrip_and_step_equivalence(mesh_pipe4):
    """VERDICT r2 #5: the interleaved layout is baked into the train state
    (no per-step cross-rank reshard). bake -> unbake is the identity, the
    baked sharded step matches the single-device depth-major step, and the
    step-1 params de-interleave back to the single-device step-1 params."""
    from pretraining_llm_tpu.parallel import pipeline as pp

    tiny = get_preset("tiny")
    cfg = tiny.replace(
        model=dataclasses.replace(
            tiny.model,
            n_layers=8,
            pipeline_stages=4,
            pipeline_microbatches=4,
            pipeline_interleave=2,
            param_dtype="float32",
            compute_dtype="float32",
        ),
        mesh=dataclasses.replace(tiny.mesh, data=2, pipe=4),
        train=dataclasses.replace(tiny.train, batch_size=8, microbatches=1),
    )
    state = ts.init_train_state(cfg, jax.random.key(0))

    # Round trip is the identity.
    baked = ts.bake_state_layout(state, cfg, forward=True)
    unbaked = ts.bake_state_layout(baked, cfg, forward=False)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state, unbaked,
    )
    # And it really permutes (layer 1 moved off slot 1).
    w = np.asarray(state["params"]["blocks"]["attn"]["wqkv"])
    wb = np.asarray(baked["params"]["blocks"]["attn"]["wqkv"])
    assert not np.array_equal(w[1], wb[1])
    # Rank-major order: rank r holds chunks (r, S+r) -> slot 1 is depth chunk 4.
    np.testing.assert_array_equal(wb[1], w[4])

    assert ts.uses_baked_layout(cfg, mesh_pipe4)
    x = jax.random.randint(jax.random.key(1), (8, cfg.model.context_length), 0,
                           cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)

    sharded = ts.shard_train_state(jax.tree.map(jnp.copy, state), mesh_pipe4, cfg)
    step = ts.build_train_step(cfg, mesh_pipe4)
    sharded, metrics = step(sharded, (x, y))

    single = ts.build_train_step(cfg, mesh=None)
    state1, metrics1 = single(state, (x, y))
    np.testing.assert_allclose(
        float(metrics["loss"]), float(metrics1["loss"]), rtol=1e-4
    )
    # Step-1 params, de-interleaved, match the single-device step-1 params.
    got = ts.bake_state_layout(jax.device_get(sharded), cfg, forward=False)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(got["params"])[0])
    for path, leaf in jax.tree_util.tree_flatten_with_path(state1["params"])[0]:
        np.testing.assert_allclose(
            np.asarray(flat_got[tuple(path)]), np.asarray(leaf),
            rtol=2e-3, atol=1e-5,
            err_msg=f"param mismatch at {jax.tree_util.keystr(path)}",
        )
