"""Cross-request prefix cache: content-addressed, copy-on-write paged-KV reuse.

The correctness bar (CPU-enforced): greedy serving outputs with the
prefix cache ON are BIT-IDENTICAL to cache OFF at every pipeline depth,
through admission churn, cancellation, and preemption. The cache is pure
host bookkeeping plus a suffix-only prefill — a configuration that
changed one emitted token would be a shared-page write (CoW violation)
or a wrong-prefix match, not a perf trade-off.

Unit layer: the content-addressed index / refcount / LRU machinery over
a real BlockAllocator. Integration layer: ServingEngine identity runs,
preemption-resume reuse, eviction-before-preemption ordering, allocator
conservation at drain, and the admission-discount / loadgen satellites.
"""

import dataclasses

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import ServingConfig, get_preset
from pretraining_llm_tpu.frontend.admission import AdmissionController
from pretraining_llm_tpu.frontend.loadgen import LoadSpec, build_schedule
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.paged import BlockAllocator
from pretraining_llm_tpu.generation.prefix_cache import STAT_KEYS, PrefixCache
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

import jax.numpy as jnp

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
DRAFT_CFG = dataclasses.replace(CFG, n_layers=1, d_model=16, n_heads=2)

DEPTHS = [1, 2, 3]
BS = 8  # block_size used throughout


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def draft_params():
    return transformer.init_params(DRAFT_CFG, jax.random.key(99))


def _shared_prefix_prompts(n, prefix_blocks=2, tail=(3, 5, 2, 6, 4, 1)):
    """n prompts sharing a block-aligned common prefix + unique tails."""
    rng = np.random.default_rng(11)
    prefix = rng.integers(0, CFG.vocab_size, size=prefix_blocks * BS).tolist()
    out = []
    for i in range(n):
        t = int(tail[i % len(tail)])
        out.append(prefix + rng.integers(0, CFG.vocab_size, size=t).tolist())
    return out


def _reference_greedy(params, cfg, prompt, n_new):
    toks = generate(
        params, cfg, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


def _run_cache_pair(params, prompts, n_new, *, depth, cancel_after=None,
                    **kw):
    """Run the SAME workload cache-off and cache-on; returns
    (off_out, on_out, on_eng) with outputs keyed by submission index
    (committed tokens streamed through on_token, so a cancelled
    request's partial output is compared too). ``cancel_after`` =
    (victim_idx, n_tokens): cancel that request once n committed tokens
    have streamed — issued BETWEEN scheduler turns, the way the online
    engine loop lands cancellations, identically in both runs."""

    def run(cache):
        eng = ServingEngine(
            params, CFG, temperature=0.0, pipeline_depth=depth,
            prefix_cache=cache, **kw,
        )
        rids = [eng.submit(p, n_new) for p in prompts]
        idx_of = {r: i for i, r in enumerate(rids)}
        streamed = {i: [] for i in range(len(prompts))}
        eng.on_token = lambda rid, tok: streamed[idx_of[rid]].append(tok)
        if cancel_after is None:
            eng.run(pipeline=True)
        else:
            victim_idx, after = cancel_after
            cancelled = False
            while eng.has_work() or eng._inflight:
                eng.pipeline_tick()
                if not cancelled and sum(map(len, streamed.values())) >= after:
                    eng.cancel(rids[victim_idx])
                    cancelled = True
        return streamed, eng

    off_out, _ = run(False)
    on_out, eng = run(True)
    return off_out, on_out, eng


# -- unit: content-addressed index / refcounts / LRU ----------------------


def _publish(cache, alloc, history, *, n_shared=0, blocks=None):
    """Allocate blocks for ``history`` and publish its full blocks the
    way _release_row does for a finished row (g=0: publish_len = len)."""
    if blocks is None:
        need = -(-len(history) // cache.block_size)
        blocks = alloc.alloc(need)
    cache.release_row(history, blocks, n_shared, len(history))
    return blocks


def test_chain_digest_binds_whole_prefix():
    """Two identical blocks under DIFFERENT parents must get different
    digests — block identity encodes the entire prefix, so a flat dict
    lookup is longest-prefix matching."""
    block = list(range(8))
    d_root = PrefixCache._chain(b"", block)
    d_child = PrefixCache._chain(d_root, block)
    assert d_root != d_child
    # And the digest is a pure function of (parent, tokens).
    assert d_root == PrefixCache._chain(b"", list(range(8)))


def test_hit_capped_one_token_short_of_prompt():
    """A prompt IDENTICAL to a published history may reuse at most
    (p-1)//bs blocks: the final prompt token always prefills privately
    (first-token logits need a real forward; the first decode write
    lands copy-on-write in a private block)."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    hist = list(range(24))  # exactly 3 full blocks
    _publish(cache, alloc, hist)
    cached, ids = cache.acquire(hist)
    assert cached == 16 and len(ids) == 2  # NOT the block containing tok 23
    cache.release_shared(ids)


def test_min_blocks_gates_short_hits():
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS, min_blocks=2)
    _publish(cache, alloc, list(range(24)))
    # Only 1 block of usable prefix (prompt is 1.5 blocks long) -> miss.
    assert cache.peek(list(range(12))) == 0
    # 2 usable blocks -> hit.
    assert cache.peek(list(range(24))) == 16


def test_acquire_refcounts_and_cold_lru_transitions():
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    _publish(cache, alloc, list(range(16)))
    assert cache.evictable == 2 and cache.cached_blocks == 2
    cached, ids = cache.acquire(list(range(17)))
    assert cached == 16 and cache.evictable == 0  # retained -> not cold
    assert cache.evict(5) == 0  # live-shared blocks are never evictable
    cache.release_shared(ids)
    assert cache.evictable == 2
    assert cache.evict(5) == 2  # now they can go
    assert cache.cached_blocks == 0


def test_peek_has_no_side_effects():
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    _publish(cache, alloc, list(range(16)))
    before = (cache.evictable, cache.cached_blocks, alloc.available)
    assert cache.peek(list(range(17))) == 16
    assert (cache.evictable, cache.cached_blocks, alloc.available) == before


def test_evict_lru_order_touch_refreshes():
    """Eviction takes the LEAST recently used cold chain first; an
    acquire/release cycle refreshes recency."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    a = _publish(cache, alloc, [1] * 8)
    b = _publish(cache, alloc, [2] * 8)
    # Touch a: it becomes most-recent; b is now the LRU.
    _, ids = cache.acquire([1] * 9)
    cache.release_shared(ids)
    assert cache.evict(1) == 1
    assert cache.peek([2] * 9) == 0  # b evicted
    assert cache.peek([1] * 9) == 8  # a survives
    assert b[0] in alloc._free and a[0] not in alloc._free


def test_duplicate_publish_first_writer_wins():
    """Two rows finishing with the same history: the second publisher's
    blocks go back to the allocator (content is identical), the index
    keeps the first — no leak, no double count."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    _publish(cache, alloc, list(range(16)))
    avail_before = alloc.available
    _publish(cache, alloc, list(range(16)))  # duplicate content
    assert cache.cached_blocks == 2
    assert alloc.available == avail_before  # dup's 2 blocks came right back


def test_release_row_frees_partial_tail_and_overgrants():
    """Only blocks wholly below publish_len are published; the partial
    tail block and speculative over-grants return to the free list."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    blocks = alloc.alloc(4)  # 2 full + 1 partial + 1 speculative
    cache.release_row(list(range(20)), blocks, 0, 20)
    assert cache.cached_blocks == 2
    assert alloc.available == 16 - 1 - 2  # all but the 2 published are free


def test_release_row_publish_len_caps_publication():
    """publish_len below a block boundary publishes nothing from that
    block — the engine passes p+g-1 because the last sampled token's
    K/V may never have been written."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    blocks = alloc.alloc(2)
    cache.release_row(list(range(16)), blocks, 0, 15)  # last slot unwritten
    assert cache.cached_blocks == 1  # only the first block is committed
    assert alloc.available == 16 - 1 - 1


def test_release_unreferenced_block_raises():
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    with pytest.raises(ValueError, match="unreferenced"):
        cache.release_shared([3])


def test_alloc_upto_cannot_cannibalize_cold_cache():
    """Cold cached blocks stay in the allocator's _live set — a
    speculative alloc_upto sweep of the whole pool must not return any
    block the LRU has not released."""
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    published = set(_publish(cache, alloc, list(range(16))))
    got = alloc.alloc_upto(32)  # ask for far more than exists
    assert not (set(got) & published)
    alloc.free(got)
    cache.flush()
    assert alloc.available == 15  # everything back, nothing lost


def test_flush_restores_allocator_exactly():
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    _publish(cache, alloc, list(range(24)))
    _publish(cache, alloc, [5] * 16)
    assert cache.flush() == cache.stats["prefix_cache_evicted_blocks"]
    assert cache.cached_blocks == 0 and alloc.available == 15


def test_stats_live_in_caller_dict():
    stats = {"other": 1}
    alloc = BlockAllocator(4)
    cache = PrefixCache(alloc, BS, stats=stats)
    for k in STAT_KEYS:
        assert stats[k] == 0
    cache.note_hit(16)
    cache.note_miss()
    assert stats["prefix_cache_hits"] == 1
    assert stats["prefix_cache_hit_tokens"] == 16
    assert stats["prefix_cache_misses"] == 1
    assert stats["other"] == 1


def test_typed_metrics_bind():
    from pretraining_llm_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry(prefix="t_")
    alloc = BlockAllocator(16)
    cache = PrefixCache(alloc, BS)
    cache.bind(reg)
    _publish(cache, alloc, list(range(16)))
    cache.note_hit(8)
    cache.note_miss()
    cache.evict(1)
    text = reg.render()
    assert "t_prefix_cache_hits_total 1" in text
    assert "t_prefix_cache_misses_total 1" in text
    assert "t_prefix_cache_hit_tokens_total 8" in text
    assert "t_prefix_cache_evicted_blocks_total 1" in text
    assert "t_prefix_cache_cached_blocks 1" in text


# -- integration: greedy bit-identity, cache on vs off --------------------


@pytest.mark.parametrize("depth", DEPTHS)
def test_cache_identity_admission_churn(params, depth):
    """Shared-prefix workload with more requests than rows: later
    admissions hit pages published by earlier finishes mid-run. Tokens
    must not move by one bit at any depth, and hits must be real."""
    prompts = _shared_prefix_prompts(6)
    n_new = 9
    off, on, eng = _run_cache_pair(
        params, prompts, n_new, depth=depth,
        max_batch=2, n_blocks=32, block_size=BS, steps_per_sched=4,
    )
    assert on == off
    assert eng.stats["prefix_cache_hits"] > 0
    assert eng.stats["prefix_cache_hit_tokens"] > 0
    for i, p in enumerate(prompts):
        assert on[i] == _reference_greedy(params, CFG, p, n_new)


@pytest.mark.parametrize("depth", DEPTHS)
def test_cache_identity_under_preemption(params, depth):
    """Pool too small for both rows' full horizon: preemption +
    recompute-on-resume with the cache publishing/evicting underneath —
    outputs exact, and the resume prefill HITS the preempted request's
    own just-published pages (unique prompts: no other source)."""
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(0, CFG.vocab_size, size=12).tolist(),
        rng.integers(0, CFG.vocab_size, size=10).tolist(),
    ]
    n_new = 24
    off, on, eng = _run_cache_pair(
        params, prompts, n_new, depth=depth,
        max_batch=2, n_blocks=8, block_size=BS, steps_per_sched=4,
    )
    assert on == off
    assert eng.stats["preemptions"] >= 1
    for i, p in enumerate(prompts):
        assert on[i] == _reference_greedy(params, CFG, p, n_new)


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_cache_identity_with_cancellation(params, depth):
    """Mid-run cancellation releases a row whose blocks publish to the
    cache; survivors and the cancelled request's partial output must be
    bit-identical to the cache-off run with the same trigger."""
    prompts = _shared_prefix_prompts(4)
    n_new = 10
    off, on, eng = _run_cache_pair(
        params, prompts, n_new, depth=depth, cancel_after=(1, 5),
        max_batch=2, n_blocks=32, block_size=BS, steps_per_sched=4,
    )
    assert on == off
    for i, p in enumerate(prompts):
        if i in on and len(on[i]) == n_new:
            assert on[i] == _reference_greedy(params, CFG, p, n_new)


def test_preemption_resume_reuses_published_pages(params):
    """The preemption-cost win: a preempted request's re-prefill must
    hit the pages it just published, dropping recompute from full
    re-prefill to tail-only. Unique prompts mean every cache hit here
    IS a resume hit."""
    rng = np.random.default_rng(5)
    prompts = [
        rng.integers(0, CFG.vocab_size, size=12).tolist(),
        rng.integers(0, CFG.vocab_size, size=10).tolist(),
    ]
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=2, n_blocks=8,
        block_size=BS, steps_per_sched=4, pipeline_depth=2,
        prefix_cache=True,
    )
    rids = [eng.submit(p, 24) for p in prompts]
    eng.run(pipeline=True)
    assert eng.stats["preemptions"] >= 1
    assert eng.stats["prefix_cache_hits"] >= 1
    # The resumed request's timing carries the accumulated savings.
    assert any(
        eng.timing_summary(r).get("cached_tokens", 0) > 0 for r in rids
    )


def test_eviction_before_preemption(params):
    """Pool pressure with a cold cache present must evict cache blocks,
    not preempt live requests: sequential single-row traffic leaves the
    pool full of cold published pages that later requests' growth must
    reclaim via the LRU."""
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, CFG.vocab_size, size=12).tolist()
               for _ in range(4)]
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=1, n_blocks=10,
        block_size=BS, steps_per_sched=4, pipeline_depth=2,
        prefix_cache=True,
    )
    for p in prompts:
        eng.submit(p, 16)
    eng.run(pipeline=True)
    assert eng.stats["prefix_cache_evicted_blocks"] >= 1
    assert eng.stats["preemptions"] == 0


def test_allocator_conserved_at_drain_and_flush(params):
    """Drain invariant with the cache on: free list + cold cache ==
    whole pool (block 0 aside); a full flush returns every block to the
    allocator with zero residue."""
    prompts = _shared_prefix_prompts(5)
    n_blocks = 32
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=2, n_blocks=n_blocks,
        block_size=BS, steps_per_sched=4, pipeline_depth=2,
        prefix_cache=True,
    )
    for p in prompts:
        eng.submit(p, 8)
    eng.run(pipeline=True)
    cache = eng.prefix_cache
    assert eng.alloc.available + cache.evictable == n_blocks - 1
    assert cache.evictable == cache.cached_blocks  # nothing still shared
    cache.flush()
    assert eng.alloc.available == n_blocks - 1
    assert cache.cached_blocks == 0


def test_cached_tokens_in_timing_summary(params):
    """Per-request cached_tokens must be block-aligned, bounded by the
    prompt, zero for the cold-start request, and positive for at least
    one later shared-prefix request."""
    prompts = _shared_prefix_prompts(4)
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=1, n_blocks=32,
        block_size=BS, steps_per_sched=4, pipeline_depth=2,
        prefix_cache=True,
    )
    rids = [eng.submit(p, 6) for p in prompts]
    eng.run(pipeline=True)
    got = [eng.timing_summary(r).get("cached_tokens", 0) for r in rids]
    assert got[0] == 0  # cold start
    assert any(v > 0 for v in got[1:])
    for v, p in zip(got, prompts):
        assert v % BS == 0 and v < len(p)


def test_min_blocks_engine_gates_hits(params):
    """min_blocks above the shared-prefix length: no hits, outputs still
    exact (the gate only changes WHAT is reused, never what is emitted)."""
    prompts = _shared_prefix_prompts(4, prefix_blocks=1)
    n_new = 6
    off, on, eng = _run_cache_pair(
        params, prompts, n_new, depth=2,
        max_batch=2, n_blocks=32, block_size=BS, steps_per_sched=4,
        prefix_cache_min_blocks=2,
    )
    assert on == off
    assert eng.stats["prefix_cache_hits"] == 0
    assert eng.stats["prefix_cache_misses"] > 0


def test_spec_serving_identity_with_cache(params, draft_params):
    """Speculative serving with the cache on: shared block ids index the
    draft pool too, so hit admissions suffix-prefill BOTH pools — greedy
    output must equal the dense-cache reference."""
    prompts = _shared_prefix_prompts(4)
    n_new = 8
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=2, n_blocks=32,
        block_size=BS, draft_params=draft_params, draft_cfg=DRAFT_CFG,
        spec_k=3, pipeline_depth=2, prefix_cache=True,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run(pipeline=True)
    assert eng.stats["prefix_cache_hits"] > 0
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_admit_batch_with_cache_identity(params):
    """Cross-window admission batching + cache: a deferred batch mixing
    hit and miss admissions splits into two prefill programs whose
    deferred first tokens merge independently — outputs exact."""
    prompts = _shared_prefix_prompts(6)
    n_new = 8
    off, on, eng = _run_cache_pair(
        params, prompts, n_new, depth=2, admit_batch=2,
        max_batch=4, n_blocks=48, block_size=BS, steps_per_sched=4,
    )
    assert on == off
    assert eng.stats["prefix_cache_hits"] > 0


def test_config_knob_validation(params):
    with pytest.raises(ValueError, match="prefix_cache_min_blocks"):
        ServingConfig(prefix_cache_min_blocks=0)
    with pytest.raises(ValueError, match="min_blocks"):
        ServingEngine(params, CFG, prefix_cache=True,
                      prefix_cache_min_blocks=0)


# -- satellites: admission discount + hot-prefix loadgen ------------------


def test_admission_discount_reduces_outstanding_charge():
    adm = AdmissionController(max_queue_depth=8, max_outstanding_tokens=100)
    t1 = adm.try_admit(40, 10, cached_tokens=32)
    assert adm.outstanding_tokens == 18  # 40 - 32 + 10
    adm.release(t1)
    assert adm.outstanding_tokens == 0


def test_admission_discount_capped_at_prompt_minus_one():
    """A stale peek can claim more cached tokens than the prompt has
    uncached slots; the discount never drops the prompt charge below 1
    (the privately-prefilled final token)."""
    adm = AdmissionController(max_queue_depth=8, max_outstanding_tokens=100)
    t = adm.try_admit(8, 4, cached_tokens=999)
    assert adm.outstanding_tokens == 1 + 4
    adm.release(t)


def test_admission_discount_buys_headroom():
    """A request that would bust the token budget fits once its cached
    prefix is discounted — cache hits buy admission headroom."""
    adm = AdmissionController(max_queue_depth=8, max_outstanding_tokens=30)
    from pretraining_llm_tpu.frontend.admission import RejectedBusy

    with pytest.raises(RejectedBusy):
        adm.try_admit(40, 10)
    t = adm.try_admit(40, 10, cached_tokens=32)
    adm.release(t)


def test_loadgen_hot_prefix_deterministic_and_shared():
    spec = LoadSpec(
        n_requests=40, mode="open", rate_rps=100.0, vocab_size=64,
        prompt_len_min=2, prompt_len_max=4, max_new_min=1, max_new_max=2,
        prefix_pool_size=4, prefix_len=16, prefix_zipf=1.5, seed=3,
    )
    a = build_schedule(spec)
    b = build_schedule(spec)
    assert [r.prompt for r in a] == [r.prompt for r in b]
    # Every prompt starts with one of exactly pool-size distinct prefixes.
    heads = {tuple(r.prompt[:16]) for r in a}
    assert 1 < len(heads) <= 4
    for r in a:
        assert 16 + 2 <= len(r.prompt) <= 16 + 4


def test_loadgen_zipf_skews_toward_hot_prefix():
    spec = LoadSpec(
        n_requests=300, mode="closed", concurrency=1, vocab_size=64,
        prompt_len_min=1, prompt_len_max=1, max_new_min=1, max_new_max=1,
        prefix_pool_size=8, prefix_len=8, prefix_zipf=2.0, seed=0,
    )
    sched = build_schedule(spec)
    counts = {}
    for r in sched:
        counts[tuple(r.prompt[:8])] = counts.get(tuple(r.prompt[:8]), 0) + 1
    top = max(counts.values())
    # zipf s=2 over 8 ranks: rank-1 carries ~62% of the mass.
    assert top > 0.4 * len(sched)


def test_loadgen_pool_off_schedule_unchanged():
    """prefix_pool_size=0 must consume NO extra rng draws: the schedule
    is byte-identical to a spec that never heard of prefix pools."""
    base = LoadSpec(n_requests=10, mode="open", rate_rps=5.0, seed=4)
    off = LoadSpec(n_requests=10, mode="open", rate_rps=5.0, seed=4,
                   prefix_pool_size=0, prefix_len=0, prefix_zipf=3.0)
    assert [r.prompt for r in build_schedule(base)] == \
        [r.prompt for r in build_schedule(off)]


def test_loadgen_prefix_validation():
    with pytest.raises(ValueError, match="prefix_len"):
        LoadSpec(prefix_pool_size=2, prefix_len=0)
    with pytest.raises(ValueError, match="prefix_pool_size"):
        LoadSpec(prefix_pool_size=-1)
    with pytest.raises(ValueError, match="prefix_zipf"):
        LoadSpec(prefix_pool_size=2, prefix_len=4, prefix_zipf=-0.5)


def test_engine_loop_surfaces_cached_tokens(params):
    """End-to-end through the frontend: terminal info (what gateway
    bodies and req_* events carry) must include cached_tokens, and the
    registry must expose the typed cache counters."""
    from pretraining_llm_tpu.frontend.engine_loop import EngineLoop
    from pretraining_llm_tpu.observability.metrics import MetricsRegistry

    registry = MetricsRegistry(prefix="t_")
    eng = ServingEngine(
        params, CFG, temperature=0.0, max_batch=2, n_blocks=32,
        block_size=BS, steps_per_sched=4, pipeline_depth=2,
        prefix_cache=True,
    )
    adm = AdmissionController(max_queue_depth=8)
    loop = EngineLoop(eng, admission=adm, registry=registry)
    prompts = _shared_prefix_prompts(3)
    with loop:
        infos = []
        for p in prompts:
            status, toks, info = loop.submit(p, 4).result(timeout=120)
            assert status == "done"
            infos.append(info)
    assert infos[0].get("cached_tokens", 0) == 0
    assert any(i.get("cached_tokens", 0) > 0 for i in infos[1:])
    text = registry.render()
    assert "t_prefix_cache_hits_total" in text
    assert "t_prefix_cache_cached_blocks" in text
