"""Offline preprocess pipeline: text files -> token memmaps -> loader."""

import numpy as np

from pretraining_llm_tpu.data import loader
from pretraining_llm_tpu.data.preprocess import preprocess, split_documents, write_token_file
from pretraining_llm_tpu.data.tokenizer import get_tokenizer


def test_split_is_deterministic():
    docs = [f"doc {i}" for i in range(100)]
    t1, v1 = split_documents(docs, 0.1, seed=42)
    t2, v2 = split_documents(docs, 0.1, seed=42)
    assert t1 == t2 and v1 == v2
    assert len(v1) == 10
    assert set(t1) | set(v1) == set(docs)


def test_write_token_file_roundtrip(tmp_path):
    docs = ["hello world", "goodbye world"]
    path = str(tmp_path / "toks.bin")
    n = write_token_file(docs, path, "byte", num_proc=1)
    tok = get_tokenizer("byte")
    data = np.memmap(path, dtype=np.uint16, mode="r")
    assert len(data) == n
    # Contents: doc1 bytes + eot + doc2 bytes + eot
    want = tok.encode_ordinary(docs[0]) + [tok.eot_token] + tok.encode_ordinary(docs[1]) + [tok.eot_token]
    np.testing.assert_array_equal(np.asarray(data), want)


def test_preprocess_end_to_end_feeds_loader(tmp_path):
    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog. " * 500)
    train_path, val_path = preprocess(
        input_files=[str(corpus)],
        out_dir=str(tmp_path / "data"),
        tokenizer_name="byte",
        val_fraction=0.05,
        num_proc=1,
    )
    it = loader.get_batch_iterator(train_path, batch_size=2, context_length=32, seed=0)
    x, y = next(it)
    assert x.shape == (2, 32)
    assert (x < 257).all()
    itv = loader.get_batch_iterator(val_path, batch_size=1, context_length=32, seed=0)
    next(itv)


def test_preprocess_jsonl(tmp_path):
    import json

    jl = tmp_path / "docs.jsonl"
    jl.write_text("\n".join(json.dumps({"text": f"document number {i} " * 30}) for i in range(20)))
    train_path, val_path = preprocess(
        input_files=[str(jl)],
        out_dir=str(tmp_path / "data"),
        tokenizer_name="byte",
        val_fraction=0.1,
        num_proc=1,
    )
    data = np.memmap(train_path, dtype=np.uint16, mode="r")
    assert len(data) > 100
