"""Out-of-process serving workers: socket fault domain, redrive storm
guards, and probe-vetted rolling weight upgrades.

The correctness bar is test_fleet.py's, moved across a process
boundary: a worker SIGKILLed mid-decode (or severed, or wedged) must
cost zero requests — every in-flight request redrives to a surviving
worker and finishes with greedy output BIT-IDENTICAL to a run that
never saw the disturbance, at every pipeline depth, prefix cache on or
off. Rolling upgrades are vetted by golden probes BEFORE the new
worker takes traffic: a corrupt (or crashing) checkpoint is refused
and the old weights restored without clients ever seeing it.

Workers build their own params from (preset, init_seed) — the same
``init_params(cfg, key(0))`` this module's reference engine uses — so
bit-identity assertions compare real decode output across processes,
not a mock.

The subprocess drills are marked ``slow`` (each spawns real worker
processes and builds engines; the module takes ~2.5 min end to end) so
the tier-1 ``-m "not slow"`` run keeps only the wire/config unit tests;
``ci_smoke.sh`` runs the full module explicitly.
"""

import dataclasses
import importlib.util
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import FrontendConfig, get_preset
from pretraining_llm_tpu.frontend.loadgen import FleetAction, run_fleet_plan
from pretraining_llm_tpu.frontend.remote_replica import RemoteReplica
from pretraining_llm_tpu.frontend.replica import Replica
from pretraining_llm_tpu.frontend.router import Router
from pretraining_llm_tpu.frontend.wire import (
    MAX_FRAME_BYTES,
    ConnectionLost,
    ProtocolError,
    encode_frame,
    recv_frame,
    send_frame,
)
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.observability.events import EventBus
from pretraining_llm_tpu.observability.export import lint_exposition
from pretraining_llm_tpu.observability.metrics import (
    MetricsRegistry,
    render_merged,
)
from pretraining_llm_tpu.resilience.faults import (
    ServingFaultInjector,
    split_serving_plan,
)

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "obs_report_for_proc_fleet", os.path.join(_REPO, "scripts", "obs_report.py")
)
obs_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(obs_report)


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)])).tolist()
        for i in range(n)
    ]


def _engine_kw(**kw):
    out = dict(
        max_batch=2, n_blocks=24, block_size=8, temperature=0.0,
        steps_per_sched=4, pipeline_depth=2,
    )
    out.update(kw)
    return out


def _worker_spec(**engine_kw):
    """Worker spec whose engine is config-identical to _undisturbed's —
    same preset, same init seed, same scheduling geometry — so outputs
    must match bit-for-bit across the process boundary."""
    return {
        "preset": "tiny",
        "init_seed": 0,
        "model_overrides": {"compute_dtype": "float32"},
        "engine": _engine_kw(**engine_kw),
        "admission": {"max_queue_depth": 8},
    }


def _undisturbed(params, prompts, n_new, **kw):
    eng = ServingEngine(params, CFG, **_engine_kw(**kw))
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return {rids[rid]: toks for rid, toks in out.items()}


def _proc_fleet(
    n=2, faults=None, bus=None, engine_kw=None, replica_kw=None, **router_kw
):
    reps = [
        RemoteReplica(
            i,
            _worker_spec(**(engine_kw or {})),
            bus=bus,
            fault_injector=faults,
            **(replica_kw or {}),
        )
        for i in range(n)
    ]
    router_kw.setdefault("eject_backoff_s", 60.0)
    return Router(reps, bus=bus, **router_kw)


def _kill_worker(rep):
    proc = rep.proc
    if proc is not None:
        proc.kill()


# -- wire framing (no JAX, no subprocess) -----------------------------------


def test_wire_roundtrip():
    a, b = socket.socketpair()
    try:
        payload = {"op": "submit", "prompt": [1, 2, 3], "rid": 7, "s": "x"}
        send_frame(a, payload)
        assert recv_frame(b) == payload
        send_frame(b, {"id": 1, "ok": True})
        assert recv_frame(a) == {"id": 1, "ok": True}
    finally:
        a.close()
        b.close()


def test_wire_peer_death_is_connection_lost():
    a, b = socket.socketpair()
    b.close()
    with pytest.raises(ConnectionLost):
        recv_frame(a)
    a.close()


def test_wire_truncated_frame_is_connection_lost():
    a, b = socket.socketpair()
    # Declare 100 bytes, deliver 3, hang up: the peer died mid-frame.
    a.sendall(struct.pack(">I", 100) + b"abc")
    a.close()
    with pytest.raises(ConnectionLost):
        recv_frame(b)
    b.close()


def test_wire_garbage_is_protocol_error_not_death():
    a, b = socket.socketpair()
    try:
        # A frame that parses as JSON but is not an object: the peer is
        # speaking garbage — NOT redrivable, must not look like death.
        body = json.dumps([1, 2]).encode()
        a.sendall(struct.pack(">I", len(body)) + body)
        with pytest.raises(ProtocolError):
            recv_frame(b)
        # Oversized declared length fails fast instead of a huge recv.
        a.sendall(struct.pack(">I", MAX_FRAME_BYTES + 1))
        with pytest.raises(ProtocolError):
            recv_frame(b)
    finally:
        a.close()
        b.close()


def test_wire_oversized_payload_refused_at_send():
    with pytest.raises(ProtocolError):
        encode_frame({"blob": "x" * (MAX_FRAME_BYTES + 16)})


# -- fault-plan split across the process boundary ---------------------------


def test_split_serving_plan():
    engine, process = split_serving_plan(
        "replica_crash@req2:r0, worker_kill@req3:r1, slow_window@req5,"
        " conn_drop@req1:r0, worker_stall@req4"
    )
    assert engine == "replica_crash@req2:r0,slow_window@req5"
    assert process == "worker_kill@req3:r1,conn_drop@req1:r0,worker_stall@req4"
    assert split_serving_plan("replica_crash@req1") == (
        "replica_crash@req1", ""
    )
    with pytest.raises(ValueError):
        split_serving_plan("worker_vaporize@req1")


def test_fleet_action_upgrade_validation():
    act = FleetAction(
        at_s=0.5, kind="upgrade", replica=0, update={"model_path": "x"}
    )
    assert act.update == {"model_path": "x"}
    with pytest.raises(ValueError):
        FleetAction(at_s=0.5, kind="kill", replica=0, update={"x": 1})
    with pytest.raises(ValueError):
        FleetAction(at_s=0.5, kind="defrag", replica=0)


def test_frontend_config_replica_mode():
    assert FrontendConfig().replica_mode == "inproc"
    assert FrontendConfig(replica_mode="process").redrive_max_attempts == 3
    with pytest.raises(ValueError, match="replica_mode"):
        FrontendConfig(replica_mode="thread")
    with pytest.raises(ValueError, match="redrive_max_attempts"):
        FrontendConfig(redrive_max_attempts=-1)


# -- worker death mid-decode: zero lost, bit-identical ----------------------


# The (depth=2, cache=False) cell of the acceptance grid lives in
# test_worker_death_obs_join_and_relaunch below, which additionally
# pins the relaunch and the offline report joins — one fleet, one set
# of worker spawns, both contracts.
_KILL_GRID = [(1, False), (1, True), (2, True), (3, False), (3, True)]


@pytest.mark.slow
@pytest.mark.parametrize("depth,cache", _KILL_GRID)
def test_worker_kill9_bit_identity(params, depth, cache):
    """SIGKILL a worker with requests mid-decode: the parent sees the
    socket die, ejects the replica, and redrives every in-flight request
    onto the survivor — final greedy outputs bit-identical to a run that
    never saw the kill, at every pipeline depth, prefix cache on/off."""
    prompts = _prompts(4)
    n_new = 6
    kw = dict(pipeline_depth=depth, prefix_cache=cache)
    ref = _undisturbed(params, prompts, n_new, **kw)

    faults = ServingFaultInjector("worker_kill@req2:r0")
    router = _proc_fleet(faults=faults, engine_kw=kw)
    with router:
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], f"request {i} diverged after worker kill"
    assert router.counters["redrives"] >= 1
    assert router.counters["ejects"] == 1
    assert sum(1 for _, _, inf in results if inf["redrives"] > 0) >= 1


@pytest.mark.slow
def test_conn_drop_redrives_without_killing_worker(params):
    """Severing the socket (worker process still healthy) must look like
    death from the parent's side: eject, redrive, zero lost — the fault
    domain is the CONNECTION, not the process."""
    prompts = _prompts(4)
    ref = _undisturbed(params, prompts, 6)
    faults = ServingFaultInjector("conn_drop@req2:r0")
    router = _proc_fleet(faults=faults)
    with router:
        reqs = [router.submit(p, 6) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        assert router.replicas[0].state == "ejected"
        # The severed worker is alive and orphan-watching; kill it so
        # teardown doesn't wait out its proc.wait grace.
        _kill_worker(router.replicas[0])
    for i, (status, tokens, _) in enumerate(results):
        assert status == "done"
        assert tokens == ref[i]
    assert router.counters["redrives"] >= 1
    assert router.counters["ejects"] >= 1


@pytest.mark.slow
def test_worker_stall_detected_by_rpc_timeout(params):
    """A wedged worker (alive, accepting bytes, never replying) is
    detected by RPC timeout + retry exhaustion, declared dead, and its
    requests redrive — the timeout path, not the EOF path."""
    prompts = _prompts(4)
    ref = _undisturbed(params, prompts, 6)
    faults = ServingFaultInjector("worker_stall@req2:r0")
    router = _proc_fleet(
        faults=faults,
        replica_kw=dict(rpc_timeout_s=0.6, rpc_retries=1),
    )
    with router:
        reqs = [router.submit(p, 6) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        stalled = router.replicas[0]
        assert stalled.registry.counter(
            "worker_rpc_timeouts_total", ""
        ).value >= 1
        assert stalled.registry.counter(
            "worker_rpc_retries_total", ""
        ).value >= 1
        # The wedged worker never answers a shutdown RPC; kill it so
        # teardown is immediate.
        _kill_worker(stalled)
    for i, (status, tokens, _) in enumerate(results):
        assert status == "done"
        assert tokens == ref[i]
    assert router.counters["ejects"] >= 1


@pytest.mark.slow
def test_worker_death_obs_join_and_relaunch(params, tmp_path):
    """The (depth=2, cache off) cell of the kill grid, plus the full
    robustness loop observable end-to-end: worker dies -> redrives
    (bit-identical) -> replica relaunched (fresh worker process) ->
    fleet healthy; the event stream passes the strict fleet gate and
    the workers section joins the death to the redrives it caused."""
    prompts = _prompts(4)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new)
    path = tmp_path / "events.jsonl"
    bus = EventBus(jsonl_path=str(path))
    faults = ServingFaultInjector("worker_kill@req2:r0", bus=bus)
    registry = MetricsRegistry("pllm_serving_")
    router = _proc_fleet(
        faults=faults, bus=bus, registry=registry, eject_backoff_s=0.2
    )
    with router:
        reqs = [router.submit(p, n_new) for p in prompts]
        for i, r in enumerate(reqs):
            status, tokens, _ = r.result(timeout=120)
            assert status == "done"
            assert tokens == ref[i], f"request {i} diverged after kill"
        assert router.counters["redrives"] >= 1
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(rep.accepting for rep in router.replicas):
                break
            time.sleep(0.05)
        assert all(rep.accepting for rep in router.replicas)
        assert router.replicas[0].generation >= 2
        assert router.counters["relaunches"] >= 1
        text = render_merged(
            [registry] + [rep.registry for rep in router.replicas]
        )
        assert lint_exposition(text) == []
        assert "pllm_serving_worker_spawns_total" in text
        assert "pllm_serving_replica_relaunch_total" in text
    bus.close()

    events = [json.loads(ln) for ln in path.read_text().splitlines()]
    report = obs_report.build_fleet_report(events)
    assert report["problems"] == []
    assert report["lost_requests"] == 0
    assert report["statuses"] == {"done": 4}
    w = report["workers"]
    assert w["spawns"] >= 3  # 2 initial + >=1 relaunch
    assert w["exits_unclean"] >= 1
    deaths = [d for d in w["process_deaths"] if d["replica"] == 0]
    assert deaths and deaths[0]["redrives_caused"] >= 1
    assert deaths[0]["respawned"]


@pytest.mark.slow
def test_worker_orphan_exits_when_parent_pipe_closes():
    """A worker whose parent vanished (stdin pipe EOF) must drain and
    exit on its own — no leaked engine processes behind a dead server."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "pretraining_llm_tpu.frontend.worker",
            "--spec-json", json.dumps(_worker_spec()),
        ],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        env=env,
    )
    try:
        line = proc.stdout.readline()
        assert json.loads(line)["worker"]["pid"] == proc.pid
        proc.stdin.close()  # the parent "dies"
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


# -- redrive storm guard ----------------------------------------------------


@pytest.mark.slow
def test_redrive_budget_exhaustion_is_terminal(params):
    """A request whose redrive budget is exhausted gets a CLEAN error
    terminal — not an infinite redrive storm — while the fleet heals and
    survivors' allocators account every block."""
    prompts = _prompts(5)
    faults = ServingFaultInjector("replica_crash@req2:r0")

    def factory():
        return ServingEngine(params, CFG, **_engine_kw())

    reps = [
        Replica(i, factory, fault_injector=faults) for i in range(2)
    ]
    router = Router(reps, eject_backoff_s=0.1, redrive_max=0)
    with router:
        reqs = [router.submit(p, 6) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        exhausted = [
            (status, info) for status, _, info in results
            if status == "error"
        ]
        assert exhausted, "the crash must have caught requests in flight"
        for status, info in exhausted:
            assert "redrive budget exhausted" in info["reason"], info
        assert all(status in ("done", "error") for status, _, _ in results)
        assert router.counters["redrives"] == 0
        # The fleet heals: the crashed replica relaunches and accepts.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(rep.accepting for rep in router.replicas):
                break
            time.sleep(0.05)
        assert all(rep.accepting for rep in router.replicas)
        # Survivor accounting: all blocks freed (one block is the
        # allocator's reserved null page, as in an undisturbed engine).
        assert reps[1].engine.alloc.available == 24 - 1


# -- probe-vetted rolling upgrades ------------------------------------------


@pytest.mark.slow
def test_rolling_upgrade_vetting_gates_traffic(params, tmp_path):
    """Clean upgrade: drained, relaunched, probe-vetted, THEN active.
    Corrupt upgrade: probes diverge on the held worker -> refused, old
    spec restored verbatim, replica re-vetted and back in service —
    clients never see the unvetted weights."""
    path = tmp_path / "events.jsonl"
    bus = EventBus(jsonl_path=str(path))
    prompts = _prompts(3)
    ref = _undisturbed(params, prompts, 6)
    router = _proc_fleet(bus=bus, probe_interval_s=60.0)
    with router:
        assert router.upgrade_replica(0) is True
        rep = router.replicas[0]
        assert rep.state == "active"
        assert rep.generation == 2

        assert router.upgrade_replica(0, {"corrupt_weights": True}) is False
        assert rep.state == "active"
        assert "corrupt_weights" not in rep.spec
        assert router.counters["upgrades"] == 2
        assert router.counters["upgrades_refused"] == 1

        reqs = [router.submit(p, 6) for p in prompts]
        for i, r in enumerate(reqs):
            status, tokens, _ = r.result(timeout=120)
            assert status == "done"
            assert tokens == ref[i]
    bus.close()

    events = [json.loads(ln) for ln in path.read_text().splitlines()]
    report = obs_report.build_fleet_report(events)
    assert report["problems"] == []
    u = report["upgrades"]
    assert u["started"] == 2
    assert u["vetted"] == 1
    assert u["refused"] == 1
    assert u["rolled_back"] == 1
    assert u["restored"] == 1


@pytest.mark.slow
def test_mid_upgrade_kill_never_exposes_unvetted_weights(params):
    """Satellite drill: the upgraded worker carries corrupt weights AND
    SIGKILLs itself on its first vetting probe, while client traffic is
    live. The upgrade must be refused, the old-weights replica restored,
    and every client answer bit-identical to an undisturbed run — proof
    traffic never touched the unvetted checkpoint."""
    prompts = _prompts(6)
    n_new = 6
    ref = _undisturbed(params, prompts, n_new)
    router = _proc_fleet(probe_interval_s=60.0)
    with router:
        plan = run_fleet_plan(router, [
            FleetAction(
                at_s=0.3, kind="upgrade", replica=0,
                update={"corrupt_weights": True, "kill_after_submits": 1},
            ),
        ])
        reqs = [router.submit(p, n_new) for p in prompts]
        results = [r.result(timeout=120) for r in reqs]
        plan.join(timeout=120)
        assert not plan.is_alive()
        rep = router.replicas[0]
        assert router.counters["upgrades_refused"] == 1
        assert rep.state == "active"
        assert "corrupt_weights" not in rep.spec
        assert "kill_after_submits" not in rep.spec
        assert all(r.accepting for r in router.replicas)
    for i, (status, tokens, info) in enumerate(results):
        assert status == "done", (i, status, info)
        assert tokens == ref[i], f"request {i} saw unvetted weights"
