"""Property-based tests (hypothesis): kernel and tokenizer invariants.

Example-based tests pin known shapes; these search the input space for the
edge cases nobody thought to write down (odd lengths, adversarial merge
orders, degenerate distributions).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from pretraining_llm_tpu.data.bpe import BPETokenizer
from pretraining_llm_tpu.ops.attention import naive_attention


@pytest.fixture(scope="module")
def trained_tok():
    corpus = [
        "the quick brown fox jumps over the lazy dog " * 10,
        "pack my box with five dozen liquor jugs " * 10,
        "aaaa abab bbbb baba " * 20,
    ]
    return BPETokenizer.train(corpus, vocab_size=320)


@settings(max_examples=60, deadline=None)
@given(st.text(max_size=400))
def test_bpe_native_equals_python_and_roundtrips(trained_tok, text):
    """For ANY text: the C++ encoder matches the Python sweep bit-for-bit
    and decode(encode(text)) == text."""
    ids = trained_tok.encode_ordinary(text)  # native when built
    want = trained_tok._encode_python(list(text.encode("utf-8")))
    assert ids == want
    assert trained_tok.decode(ids) == text
    assert all(0 <= i < trained_tok.n_vocab for i in ids)


@settings(max_examples=25, deadline=None)
@given(
    st.integers(min_value=1, max_value=48),  # Tq
    st.integers(min_value=1, max_value=48),  # Tk
    st.integers(min_value=0, max_value=2**31 - 1),
)
def test_causal_attention_ignores_future(tq, tk, seed):
    """Changing K/V strictly in the future of every query must not change
    the output — for arbitrary (Tq, Tk) offsets of the cached-decode form."""
    key = jax.random.key(seed)
    ks = jax.random.split(key, 4)
    b, h, dh = 1, 2, 8
    q = jax.random.normal(ks[0], (b, tq, h, dh))
    k = jax.random.normal(ks[1], (b, tk, h, dh))
    v = jax.random.normal(ks[2], (b, tk, h, dh))
    q_pos = jnp.arange(tq) + max(tk - tq, 0)  # aligned suffix (decode form)
    out = naive_attention(q, k, v, causal=True, q_positions=q_pos)

    # Perturb only positions strictly after the LAST query's position.
    last = int(q_pos[-1])
    if last + 1 >= tk:
        return  # no future to perturb
    noise = jax.random.normal(ks[3], (b, tk - last - 1, h, dh)) * 100.0
    k2 = k.at[:, last + 1 :].add(noise)
    v2 = v.at[:, last + 1 :].add(noise)
    out2 = naive_attention(q, k2, v2, causal=True, q_positions=q_pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-6, atol=1e-6)


@settings(max_examples=20, deadline=None)
@given(
    st.sampled_from([16, 24, 32, 48, 64]),
    st.integers(min_value=0, max_value=2**31 - 1),
)
def test_blockwise_attention_matches_naive_any_length(t, seed):
    """The online-softmax blockwise path == dense softmax for lengths that
    do and don't divide the block sizes."""
    from pretraining_llm_tpu.ops.flash_attention import blockwise_attention

    ks = jax.random.split(jax.random.key(seed), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 8)) for kk in ks)
    want = naive_attention(q, k, v, causal=True)
    got = blockwise_attention(q, k, v, causal=True, block_q=16, block_kv=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
