"""Int8 serving quantization: per-channel weights, int8 KV pool, identity.

Three layers of guarantees:

  unit      quantize/dequantize roundtrip is bounded by half a scale step
            per element over a property grid (random, outlier rows, zero
            rows), and the param-tree transform touches exactly the
            serving projections (embeddings/norms/lm_head stay floating);
  capacity  the int8-kv pool (int8 pages + bf16 per-token scales) packs
            >= 1.9x the blocks of the bf16 pool at head_dim 64 for the
            same HBM budget — the headline the mode exists for;
  identity  greedy serving output is DETERMINISTIC WITHIN the quantized
            graph: bit-equal across pipeline depths 1-3 x prefix-cache
            on/off x chunked prefill on/off, and a corrupted shared page
            under kv_checksum is dropped and re-prefilled with no output
            divergence. Quantized output is never compared against the
            bf16 graph bit-for-bit — only against itself (the sentinel
            pins probes the same way).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import quantize, transformer
from pretraining_llm_tpu.resilience import integrity
from pretraining_llm_tpu.resilience.faults import ServingFaultInjector

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
BS = 8


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def qparams(params):
    return quantize.quantize_params_for_serving(params, CFG)


# -- quantize/dequantize roundtrip property grid -----------------------------


def _grid_weight(case, shape, rng):
    w = rng.normal(size=shape).astype(np.float32)
    if case == "outlier":
        # One huge element per output channel stresses the per-channel
        # scale: everything else in that channel collapses toward zero
        # codes, but the bound below must still hold.
        flat = w.reshape(-1, shape[-1])
        flat[0] *= 1e4
    elif case == "zero":
        # Whole zero channels: scale clamps at eps instead of dividing
        # by zero, and dequantized zeros stay exactly zero.
        w[..., : shape[-1] // 2] = 0.0
    return jnp.asarray(w)


@pytest.mark.parametrize("case", ["normal", "outlier", "zero"])
@pytest.mark.parametrize(
    "shape,axes",
    [
        ((16, 24), (0,)),                # plain (D, F)
        ((3, 10, 2, 4, 6), (1,)),        # stacked (L, D, 2, G, Dh)
        ((2, 4, 6, 12), (1, 2)),         # stacked wo (L, H, Dh, D)
    ],
)
def test_quantize_roundtrip_bound(case, shape, axes):
    rng = np.random.default_rng(hash((case, shape)) % 2**31)
    w = _grid_weight(case, shape, rng)
    q, scale = quantize.quantize_weight(w, axes)
    assert q.dtype == jnp.int8 and scale.dtype == jnp.float32
    expect_scale = tuple(
        1 if ax in axes else n for ax, n in enumerate(shape)
    )
    assert scale.shape == expect_scale
    assert int(jnp.max(jnp.abs(q.astype(jnp.int32)))) <= 127
    deq = quantize.dequantize_weight(q, scale, jnp.float32)
    # Symmetric rounding: each element lands within half a quantization
    # step of its channel, whatever the channel's dynamic range.
    err = np.abs(np.asarray(deq) - np.asarray(w))
    # One code step spans `scale` (= amax/127) in weight space.
    bound = np.broadcast_to(np.asarray(scale) * (0.5 + 1e-3), shape)
    assert np.all(err <= bound + 1e-30), float((err - bound).max())
    if case == "zero":
        assert np.all(np.asarray(deq)[..., : shape[-1] // 2] == 0.0)


def test_quantize_params_structure(params, qparams):
    assert not quantize.is_quantized(params)
    assert quantize.is_quantized(qparams)
    blk = qparams["blocks"]
    for name in ("wqkv",) if "wqkv" in blk["attn"] else ("wq", "wkv"):
        assert blk["attn"][name].dtype == jnp.int8
        scale = blk["attn"][name + "_scale"]
        assert scale.dtype == jnp.float32
    assert blk["mlp"]["w1"].dtype == jnp.int8
    assert blk["mlp"]["w2"].dtype == jnp.int8
    # Embeddings / norms / biases stay floating — they are tiny and their
    # precision anchors the residual stream.
    assert jnp.issubdtype(
        qparams["tok_embed"]["embedding"].dtype, jnp.floating
    )
    for norm in ("ln1", "ln2"):
        for leaf in jax.tree_util.tree_leaves(blk[norm]):
            assert jnp.issubdtype(leaf.dtype, jnp.floating)
    # The transform did not mutate its input tree.
    assert params["blocks"]["mlp"]["w1"].dtype != jnp.int8
    # Quantized model bytes shrink (int8 codes + small scale leaves).
    assert quantize.param_bytes(qparams) < quantize.param_bytes(params)


def test_quantize_rejects_moe(params):
    moe_cfg = dataclasses.replace(CFG, n_experts=4)
    with pytest.raises(ValueError, match="[Mm]o[Ee]|experts"):
        quantize.quantize_params_for_serving(params, moe_cfg)


def test_quantized_forward_close_to_exact(params, qparams):
    """Not bit-equal — int8 is lossy — but the logits must stay close on
    the scale of their own spread (the accuracy caveat README documents)."""
    tok = jnp.asarray(
        np.random.default_rng(0).integers(0, CFG.vocab_size, size=(2, 12)),
        jnp.int32,
    )
    exact, _ = transformer.forward(params, tok, CFG)
    quant, _ = transformer.forward(qparams, tok, CFG)
    spread = float(jnp.max(exact) - jnp.min(exact))
    diff = float(jnp.max(jnp.abs(exact - quant)))
    assert diff < 0.05 * spread, (diff, spread)


# -- pool capacity -----------------------------------------------------------


def test_int8_kv_pool_capacity_ratio_at_dh64():
    """At head_dim 64 the int8-kv layout (int8 pages + bf16 per-token
    scales) must hold >= 1.9x the blocks of the bf16 pool for the same
    byte budget — the acceptance bar for the mode."""
    cfg = dataclasses.replace(CFG, d_model=256)
    assert cfg.head_dim == 64, "grid assumes Dh=64"
    bf16 = transformer.make_paged_kv_pool(cfg, 4, BS, dtype="bfloat16")
    q8 = transformer.make_paged_kv_pool(
        dataclasses.replace(cfg, kv_cache_dtype="int8"), 4, BS,
        scale_dtype="bfloat16",
    )

    def pool_bytes(pools):
        return sum(l.nbytes for l in jax.tree_util.tree_leaves(pools))

    ratio = pool_bytes(bf16) / pool_bytes(q8)
    assert ratio >= 1.9, ratio
    # f32 scales would NOT clear the bar (Dh+4 per token vs Dh+2): the
    # scale dtype is a load-bearing choice, pin it.
    q8_f32 = transformer.make_paged_kv_pool(
        dataclasses.replace(cfg, kv_cache_dtype="int8"), 4, BS,
        scale_dtype="float32",
    )
    assert pool_bytes(bf16) / pool_bytes(q8_f32) < 1.9


def test_engine_pool_info_reports_layout(params):
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=16, block_size=BS,
        temperature=0.0, quantize="int8-kv",
    )
    info = eng.pool_info()
    assert info["quantize"] == "int8-kv"
    assert info["kv_dtype"] == "int8"
    assert info["kv_scale_dtype"] == "bfloat16"
    assert info["n_blocks"] == 16 and info["block_size"] == BS
    assert info["pool_bytes"] == info["bytes_per_block"] * 16
    exact = ServingEngine(
        params, CFG, max_batch=2, n_blocks=16, block_size=BS,
        temperature=0.0,
    )
    xinfo = exact.pool_info()
    assert xinfo["quantize"] == "none" and xinfo["kv_scale_dtype"] is None
    assert xinfo["bytes_per_block"] > info["bytes_per_block"]


# -- greedy identity within the quantized graph ------------------------------


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    return [
        rng.integers(0, CFG.vocab_size, size=int(lengths[i % len(lengths)]))
        .tolist()
        for i in range(n)
    ]


def _serve(params, prompts, n_new, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("n_blocks", 24)
    kw.setdefault("block_size", BS)
    kw.setdefault("steps_per_sched", 4)
    eng = ServingEngine(params, CFG, temperature=0.0, **kw)
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = eng.run()
    return [out[r] for r in sorted(rids, key=rids.get)]


def test_int8_weights_only_matches_quantized_generate(params, qparams):
    """quantize='int8' leaves the KV pool exact, so the serving engine
    must reproduce the reference generate path run on the SAME quantized
    params bit-for-bit — the identity chain that anchors every other
    serving test, shifted into the quantized graph."""
    prompts = _prompts(3)
    n_new = 8
    got = _serve(params, prompts, n_new, quantize="int8")
    for p, toks in zip(prompts, got):
        ref = generate(
            qparams, CFG, jnp.asarray([p], jnp.int32), n_new,
            jax.random.key(7), temperature=0.0,
        )
        assert toks == np.asarray(ref)[0].tolist()


def test_int8_kv_bit_identity_grid(params):
    """The acceptance grid: pipeline depths 1-3 x prefix-cache on/off x
    chunked prefill on/off, all bit-equal to each other (and run-to-run)
    WITHIN the int8-kv graph. Scheduling and caching may change which
    lane computes a token, never its value."""
    prompts = _prompts(4)
    n_new = 8
    base = _serve(params, prompts, n_new, quantize="int8-kv")
    assert base == _serve(params, prompts, n_new, quantize="int8-kv")
    for depth in (1, 2, 3):
        for pfx in (False, True):
            for chunk in (0, BS):
                got = _serve(
                    params, prompts, n_new, quantize="int8-kv",
                    pipeline_depth=depth, prefix_cache=pfx,
                    prefill_chunk_tokens=chunk,
                )
                assert got == base, (depth, pfx, chunk)


def test_int8_kv_prequantized_params_accepted(params, qparams):
    """An engine handed already-quantized params (the fleet path: serve.py
    quantizes once, N replicas share the tree) must not re-quantize and
    must produce the same outputs as one that quantizes internally."""
    prompts = _prompts(2)
    a = _serve(params, prompts, 6, quantize="int8-kv")
    b = _serve(qparams, prompts, 6, quantize="int8-kv")
    assert a == b


# -- integrity: fingerprints, digests, corrupt-page drill --------------------


def test_weight_fingerprint_covers_int8_codes(qparams):
    fp = integrity.weight_fingerprint(qparams)
    mutated = jax.tree_util.tree_map(lambda x: x, qparams)
    blk = dict(mutated["blocks"])
    mlp = dict(blk["mlp"])
    mlp["w1"] = mlp["w1"].at[(0,) * mlp["w1"].ndim].add(3)
    blk["mlp"] = mlp
    mutated = {**mutated, "blocks": blk}
    assert integrity.weight_fingerprint(mutated) != fp


def test_corrupt_weights_fires_on_quantized_replica(params):
    """The sentinel drill's corruption primitive must still find a
    floating leaf to negate on a quantized engine (the embedding stays
    bf16/f32) and the fingerprint must move."""
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=16, block_size=BS,
        temperature=0.0, quantize="int8-kv",
    )
    fp = integrity.weight_fingerprint(eng.params)
    assert ServingFaultInjector._fire_corrupt_weights(eng)
    assert integrity.weight_fingerprint(eng.params) != fp


def _shared_prefix_prompts(n, prefix_blocks=2, tail=(3, 5, 2, 6, 4, 1)):
    rng = np.random.default_rng(11)
    prefix = rng.integers(0, CFG.vocab_size, size=prefix_blocks * BS).tolist()
    return [
        prefix
        + rng.integers(0, CFG.vocab_size, size=int(tail[i % len(tail)]))
        .tolist()
        for i in range(n)
    ]


def test_corrupt_quantized_page_dropped_bit_identically(params):
    """corrupt_kv_page on an int8-kv pool flips quantized code pages AND
    their scale leaves; verify-on-acquire (kv_checksum) must drop the
    page and re-prefill privately with outputs bit-equal to the
    undisturbed quantized run."""
    prompts = _shared_prefix_prompts(4)
    n_new = 6
    ref = _serve(params, prompts * 2, n_new, quantize="int8-kv",
                 prefix_cache=False)

    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=24, block_size=BS,
        steps_per_sched=4, temperature=0.0, quantize="int8-kv",
        prefix_cache=True, kv_checksum=True,
    )
    rids = {eng.submit(p, n_new): i for i, p in enumerate(prompts)}
    out = {rids[r]: t for r, t in eng.run().items()}
    cached = eng.prefix_cache.cached_block_ids()
    assert cached
    before = integrity.kv_block_digest(eng.pools, cached[0])
    assert ServingFaultInjector._fire_corrupt_kv_page(eng)
    # The digest moved: the poison reached the quantized bytes/scales.
    assert integrity.kv_block_digest(eng.pools, cached[0]) != before
    rids2 = {eng.submit(p, n_new): len(prompts) + i
             for i, p in enumerate(prompts)}
    out.update({rids2[r]: t for r, t in eng.run().items() if r in rids2})
    assert eng.stats.get("kv_mismatches", 0) >= 1
    for i in range(len(prompts) * 2):
        assert out[i] == ref[i], f"request {i} diverged past a corrupt page"


def test_golden_probes_pin_within_quantized_graph(qparams):
    """build_probe_set on quantized params pins quantized-graph
    continuations: re-running the probes on the same tree is bit-equal;
    running them on a differently-corrupted tree diverges (what the
    router's quarantine drill keys on)."""
    probes = integrity.build_probe_set(
        qparams, CFG, n_probes=2, probe_len=9, max_new=4
    )
    again = integrity.build_probe_set(
        qparams, CFG, n_probes=2, probe_len=9, max_new=4
    )
    assert [p.expected for p in probes] == [p.expected for p in again]


# -- config / sharding plumbing ---------------------------------------------


def test_serving_config_validates_quantize():
    from pretraining_llm_tpu.config import ServingConfig

    ServingConfig(quantize="int8-kv")
    with pytest.raises(ValueError, match="serving.quantize"):
        ServingConfig(quantize="fp4")


def test_scale_leaves_get_pspecs(qparams):
    """Every *_scale leaf must resolve to a PartitionSpec of its own rank
    so shard_params_for_inference can lay the quantized tree out on a TP
    mesh without falling through to a mis-ranked weight rule."""
    from jax.sharding import PartitionSpec as P

    from pretraining_llm_tpu.parallel.sharding import param_pspec_tree

    specs = param_pspec_tree(qparams, tensor_size=2)
    flat_p = jax.tree_util.tree_flatten_with_path(qparams)[0]
    flat_s = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    assert len(flat_p) == len(flat_s)
    for (path, leaf), spec in zip(flat_p, flat_s):
        spec_t = tuple(spec) + (None,) * (leaf.ndim - len(tuple(spec)))
        assert len(spec_t) == leaf.ndim, (path, spec)
        # A sharded dim must divide evenly on this leaf for tensor=2.
        for ax, name in enumerate(spec_t):
            if name == "tensor":
                assert leaf.shape[ax] % 2 == 0, (path, spec, leaf.shape)
