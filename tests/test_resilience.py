"""Resilience subsystem: anomaly detection, rollback, watchdog, fault
injection, checkpoint-corruption recovery, and the supervisor relauncher.

The e2e tests drive the full loop the package exists for — inject a fault,
detect it, recover, finish training — on CPU, through the real Trainer.
Subprocess tests (watchdog exit codes, supervisor relaunch) reuse the
test_multiprocess.py idiom: single-device children, XLA_FLAGS stripped.
"""

import dataclasses
import json
import math
import os
import subprocess
import sys
import time

import pytest

from pretraining_llm_tpu.config import ResilienceConfig, get_preset
from pretraining_llm_tpu.resilience import (
    EXIT_WEDGED,
    Anomaly,
    AnomalyDetector,
    StepWatchdog,
    parse_faults,
)
from pretraining_llm_tpu.resilience.faults import truncate_leaf
from pretraining_llm_tpu.training import checkpoint as ckpt
from pretraining_llm_tpu.training.trainer import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "scripts", "train.py")
SUPERVISOR = os.path.join(REPO, "scripts", "supervisor.py")


def _rcfg(**kw):
    return ResilienceConfig(anomaly_detection=True, **kw)


def _resilient_config(tmp_path, **overrides):
    cfg = get_preset("tiny")
    train_kw = {
        "train_steps": 16,
        "checkpoint_interval": 4,
        "log_interval": 2,
        "eval_interval": 0,
        "checkpoint_dir": str(tmp_path / "ck"),
        "metrics_path": str(tmp_path / "metrics.jsonl"),
    }
    res_kw = {"anomaly_detection": True}
    for key, val in overrides.items():
        section, _, name = key.partition(".")
        (train_kw if section == "train" else res_kw)[name] = val
    return cfg.replace(
        train=dataclasses.replace(cfg.train, **train_kw),
        resilience=ResilienceConfig(**res_kw),
    )


def _events(tmp_path):
    path = tmp_path / "metrics.jsonl"
    if not path.exists():
        return []
    return [json.loads(line) for line in path.read_text().splitlines()]


# ---------------------------------------------------------------- unit: config


def test_resilience_config_validates():
    with pytest.raises(ValueError):
        ResilienceConfig(anomaly_window=1)
    with pytest.raises(ValueError):
        ResilienceConfig(loss_spike_factor=1.0)
    with pytest.raises(ValueError):
        ResilienceConfig(rollback_budget=-1)
    with pytest.raises(ValueError):
        ResilienceConfig(faults="nan@0")
    with pytest.raises(ValueError):
        ResilienceConfig(faults="frobnicate@5")
    ResilienceConfig(faults="nan@9, sigterm@20")  # valid plan constructs


def test_parse_faults():
    assert parse_faults("nan@9,sigterm@20") == [("nan", 9), ("sigterm", 20)]
    with pytest.raises(ValueError, match="empty"):
        parse_faults("")  # an all-empty plan is a config typo, not a no-op
    with pytest.raises(ValueError, match="hang"):
        parse_faults("hang")  # missing @step
    with pytest.raises(ValueError, match="bogus"):
        parse_faults("bogus@3")


# -------------------------------------------------------------- unit: detector


def test_detector_flags_nonfinite_immediately():
    det = AnomalyDetector(_rcfg())
    # NaN/Inf checks are armed from the first sample — no warmup.
    a = det.observe(1, {"loss": float("nan"), "grad_norm": 1.0})
    assert a is not None and a.kind == "nan"
    a = det.observe(2, {"loss": 2.0, "grad_norm": float("inf")})
    assert a is not None and a.kind == "nan"


def test_detector_spike_needs_history():
    det = AnomalyDetector(_rcfg(anomaly_min_history=5, loss_spike_factor=3.0))
    # Below min_history no spike can fire, however large the value.
    for step in range(1, 5):
        assert det.observe(step, {"loss": 2.0, "grad_norm": 1.0}) is None
    assert det.observe(5, {"loss": 1000.0, "grad_norm": 1.0}) is None
    for step in range(6, 8):
        assert det.observe(step, {"loss": 2.0, "grad_norm": 1.0}) is None
    a = det.observe(8, {"loss": 50.0, "grad_norm": 1.0})
    assert a is not None and a.kind == "loss_spike"
    # The spike was NOT folded into the baseline: an immediately following
    # normal sample is clean, and the same spike re-fires.
    assert det.observe(9, {"loss": 2.0, "grad_norm": 1.0}) is None
    assert det.observe(10, {"loss": 50.0, "grad_norm": 1.0}) is not None


def test_detector_grad_spike_and_reset():
    det = AnomalyDetector(_rcfg(anomaly_min_history=3, grad_spike_factor=10.0))
    for step in range(1, 6):
        assert det.observe(step, {"loss": 2.0, "grad_norm": 0.5}) is None
    a = det.observe(6, {"loss": 2.0, "grad_norm": 25.0})
    assert a is not None and a.kind == "grad_spike"
    det.reset()
    # Post-reset the baseline is empty again: spikes need fresh history.
    assert det.observe(7, {"loss": 2.0, "grad_norm": 25.0}) is None


def test_anomaly_event_shape():
    event = Anomaly("loss_spike", 10, 50.0, 6.0).as_event()
    assert event["event"] == "anomaly_detected"
    assert event["kind"] == "loss_spike"
    assert event["step"] == 10


# -------------------------------------------------------------- unit: watchdog


def test_watchdog_fires_and_reports_exit_code():
    codes = []
    timeouts = []
    dog = StepWatchdog(
        0.2,
        on_timeout=lambda: timeouts.append(True),
        exit_fn=codes.append,
    ).start()
    try:
        dog.heartbeat()  # arm
        deadline = time.monotonic() + 5.0
        while not dog.fired and time.monotonic() < deadline:
            time.sleep(0.05)
        assert dog.fired
        assert codes == [EXIT_WEDGED]
        assert timeouts == [True]
    finally:
        dog.stop()


def test_watchdog_heartbeats_keep_it_quiet():
    codes = []
    dog = StepWatchdog(0.4, exit_fn=codes.append).start()
    try:
        for _ in range(6):
            dog.heartbeat()
            time.sleep(0.1)
        assert not dog.fired and codes == []
    finally:
        dog.stop()
    # ...and it never fires before the first heartbeat arms it (compile time).
    lazy = StepWatchdog(0.2, exit_fn=codes.append).start()
    try:
        time.sleep(0.5)
        assert not lazy.fired and codes == []
    finally:
        lazy.stop()


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        StepWatchdog(0.0)


def test_watchdog_pause_covers_slow_offpath_work():
    """A save/eval longer than the timeout must not fire while paused."""
    codes = []
    dog = StepWatchdog(0.2, exit_fn=codes.append).start()
    try:
        dog.heartbeat()  # arm
        dog.pause()
        time.sleep(0.6)  # "slow checkpoint save": 3x the timeout
        assert not dog.fired and codes == []
        dog.resume()
        # resume() re-armed with a fresh beat: paused time isn't charged...
        time.sleep(0.1)
        assert not dog.fired
        # ...but a genuine post-resume stall still fires.
        deadline = time.monotonic() + 5.0
        while not dog.fired and time.monotonic() < deadline:
            time.sleep(0.05)
        assert dog.fired and codes == [EXIT_WEDGED]
    finally:
        dog.stop()


def test_watchdog_pause_before_arming_stays_disarmed():
    """pause/resume before the first heartbeat must not arm the watchdog —
    compile time stays excluded."""
    codes = []
    dog = StepWatchdog(0.2, exit_fn=codes.append).start()
    try:
        dog.pause()
        dog.resume()
        time.sleep(0.5)
        assert not dog.fired and codes == []
    finally:
        dog.stop()


# ------------------------------------------------- checkpoint corruption


def _write_two_checkpoints(tmp_path):
    """Train 8 steps with interval 4 -> step-4 and step-8 on disk."""
    cfg = _resilient_config(tmp_path, **{"train.train_steps": 8})
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    trainer.train()
    ckdir = cfg.train.checkpoint_dir
    assert sorted(ckpt._list_steps(ckdir)) == [4, 8]
    return cfg, ckdir


def test_restore_skips_truncated_leaf(tmp_path):
    cfg, ckdir = _write_two_checkpoints(tmp_path)
    truncate_leaf(os.path.join(ckdir, "step-8"))
    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 4
    kinds = [e.get("event") for e in _events(tmp_path)]
    assert "checkpoint_skipped" in kinds


def test_restore_skips_missing_metadata(tmp_path):
    cfg, ckdir = _write_two_checkpoints(tmp_path)
    os.remove(os.path.join(ckdir, "step-8", "metadata.json"))
    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 4


def test_restore_ignores_and_gcs_partial_tmp_dir(tmp_path):
    cfg, ckdir = _write_two_checkpoints(tmp_path)
    partial = os.path.join(ckdir, "tmp-12")
    os.makedirs(partial)
    with open(os.path.join(partial, "half_written.npy"), "wb") as f:
        f.write(b"\x93NUMPY garbage")
    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 8
    assert not os.path.exists(partial)  # GC'd on restore


def test_all_checkpoints_corrupt_refuses_to_reinitialize(tmp_path):
    cfg, ckdir = _write_two_checkpoints(tmp_path)
    for step in (4, 8):
        os.remove(os.path.join(ckdir, f"step-{step}", "metadata.json"))
    with pytest.raises(RuntimeError, match="none are loadable"):
        Trainer(cfg, synthetic_data=True, resume=True)


# ------------------------------------------------------------ e2e: in-process


def test_nan_injection_rolls_back_and_completes(tmp_path):
    """The headline loop: NaN at step 9 -> detected at the step-10 log
    boundary -> rollback to step-8 -> data frontier skips the poison window
    -> training still reaches step 16 with finite loss."""
    cfg = _resilient_config(tmp_path, **{"resilience.faults": "nan@9"})
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    final = trainer.train()
    assert trainer.exit_reason == "completed"
    assert math.isfinite(final["loss"])

    events = _events(tmp_path)
    kinds = [e.get("event") for e in events]
    assert "fault_injected" in kinds
    assert "anomaly_detected" in kinds
    rollbacks = [e for e in events if e.get("event") == "rollback"]
    assert len(rollbacks) == 1
    assert rollbacks[0]["from_step"] == 10
    assert rollbacks[0]["to_step"] == 8
    assert rollbacks[0]["skipped_batches"] == 2
    # Training genuinely continued past the rollback to the target step.
    steps = [e["step"] for e in events if "loss" in e and "step" in e]
    assert steps[-1] == 16
    assert all(math.isfinite(e["loss"]) for e in events if "loss" in e and e["step"] > 10)


def test_rollback_budget_exhaustion_stops_the_run(tmp_path):
    # 14 steps (NOT a multiple of checkpoint_interval=4): with the run
    # breaking early, an unguarded save_final would persist the poisoned
    # (NaN) state as a mislabeled step-14 — newest in the dir, corrupting
    # every later resume.
    cfg = _resilient_config(
        tmp_path,
        **{
            "train.train_steps": 14,
            "resilience.faults": "nan@9",
            "resilience.rollback_budget": 0,
        },
    )
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    trainer.train()
    assert trainer.exit_reason == "anomaly_budget"
    kinds = [e.get("event") for e in _events(tmp_path)]
    assert "rollback_budget_exhausted" in kinds
    # Newest on disk stays the last good in-loop save (step-8: the run
    # broke at the step-10 log boundary), and resume lands on it.
    assert max(ckpt._list_steps(cfg.train.checkpoint_dir)) == 8
    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 8


def test_anomaly_without_checkpoint_stops_the_run(tmp_path):
    cfg = _resilient_config(
        tmp_path,
        **{"train.checkpoint_interval": 0, "resilience.faults": "nan@3"},
    )
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    trainer.train()
    assert trainer.exit_reason == "anomaly_no_checkpoint"


def test_sigterm_fault_checkpoints_and_reports_preempted(tmp_path):
    cfg = _resilient_config(tmp_path, **{"resilience.faults": "sigterm@6"})
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    trainer.train()
    assert trainer.exit_reason == "preempted"
    # The preemption path checkpointed at the stop boundary.
    assert max(ckpt._list_steps(cfg.train.checkpoint_dir)) >= 6


def test_ckpt_truncate_fault_then_resume_falls_back(tmp_path):
    """Torn-write drill end-to-end: the fault truncates a leaf of step-8
    right after it lands; a later resume must dig back to step-4."""
    # 9 steps, not 8: the fault fires at the top of the loop iteration
    # AFTER step 8's checkpoint lands, so the run must still have one
    # iteration left to execute. save_final off, or the end-of-run step-9
    # checkpoint would mask the torn step-8.
    cfg = _resilient_config(
        tmp_path,
        **{
            "train.train_steps": 9,
            "train.save_final": False,
            "resilience.faults": "ckpt_truncate@8",
        },
    )
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    trainer.train()
    kinds = [e.get("event") for e in _events(tmp_path)]
    assert "fault_injected" in kinds
    t2 = Trainer(cfg, synthetic_data=True, resume=True)
    assert t2.start_step == 4


def test_resumed_run_does_not_refire_spent_faults(tmp_path):
    cfg = _resilient_config(tmp_path, **{"resilience.faults": "nan@9"})
    trainer = Trainer(cfg, synthetic_data=True, resume=False)
    trainer.train()
    assert trainer.exit_reason == "completed"
    # Resume from the final checkpoint (step 16 == train_steps): a second
    # train() call in a fresh Trainer must not re-inject nan@9.
    more = cfg.replace(train=dataclasses.replace(cfg.train, train_steps=20))
    t2 = Trainer(more, synthetic_data=True, resume=True)
    assert t2.start_step == 16
    final = t2.train()
    assert t2.exit_reason == "completed"
    assert math.isfinite(final["loss"])
    injected = [
        e for e in _events(tmp_path) if e.get("event") == "fault_injected"
    ]
    assert len(injected) == 1  # only the first run's


# ------------------------------------------------------------ e2e: subprocess


def _run_child(cmd, timeout):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # children run single-device: fast compile
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        cmd,
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"child timed out:\n{out[-3000:]}")
    return proc.returncode, out


def _train_cmd(ckdir, steps=20, extra=()):
    return [
        sys.executable, TRAIN, "--preset", "tiny", "--data", "synthetic",
        "--steps", str(steps), "--override",
        f"train.checkpoint_dir={ckdir}",
        "train.log_interval=2", "train.checkpoint_interval=5",
        *extra,
    ]


@pytest.mark.slow
def test_watchdog_exits_wedged_with_emergency_checkpoint(tmp_path):
    ckdir = str(tmp_path / "ck")
    rc, out = _run_child(
        _train_cmd(ckdir, extra=[
            "resilience.watchdog_timeout_s=2.0", "resilience.faults=hang@6",
        ]),
        timeout=240,
    )
    assert rc == EXIT_WEDGED, out[-3000:]
    # The watchdog persisted the last completed step before exiting...
    assert 6 in ckpt._list_steps(ckdir), out[-3000:]
    # ...and dumped thread stacks for the postmortem.
    assert "watchdog" in out and "_fire_hang" in out, out[-3000:]


@pytest.mark.slow
def test_supervisor_relaunches_after_wedge_and_completes(tmp_path):
    ckdir = str(tmp_path / "ck")
    cmd = [
        sys.executable, SUPERVISOR,
        "--max-restarts", "3", "--backoff-base", "0.2", "--",
        *_train_cmd(ckdir, extra=[
            "resilience.watchdog_timeout_s=2.0", "resilience.faults=hang@6",
        ]),
    ]
    rc, out = _run_child(cmd, timeout=420)
    assert rc == 0, out[-3000:]
    # First launch wedged at 6; the relaunch resumed (hang@6 <= start step
    # is spent) and ran to the target.
    assert 20 in ckpt._list_steps(ckdir), out[-3000:]
    sup = [json.loads(l) for l in out.splitlines() if l.startswith('{"supervisor"')]
    sup_events = [e["event"] for e in sup]
    assert sup_events.count("launch") == 2
    assert "relaunch" in sup_events
    exits = [e["rc"] for e in sup if e["event"] == "exit"]
    assert exits == [EXIT_WEDGED, 0]


def test_supervisor_gives_up_on_anomaly_exit_code(tmp_path):
    """EXIT_ANOMALY is fatal: the supervisor must NOT relaunch."""
    marker = tmp_path / "launches.txt"
    child = (
        "import sys, pathlib; "
        f"p = pathlib.Path({str(marker)!r}); "
        "p.write_text(p.read_text() + 'x' if p.exists() else 'x'); "
        "sys.exit(44)"
    )
    cmd = [
        sys.executable, SUPERVISOR, "--max-restarts", "5",
        "--backoff-base", "0.05", "--", sys.executable, "-c", child,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 44
    assert marker.read_text() == "x"  # exactly one launch


def test_supervisor_restart_budget(tmp_path):
    """A persistent crash burns the restart budget then surfaces the code."""
    cmd = [
        sys.executable, SUPERVISOR, "--max-restarts", "2",
        "--backoff-base", "0.05", "--",
        sys.executable, "-c", "import sys; sys.exit(7)",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 7
    sup = [
        json.loads(l) for l in proc.stdout.splitlines()
        if l.startswith('{"supervisor"')
    ]
    assert [e["event"] for e in sup].count("launch") == 3  # 1 + 2 restarts


def test_supervisor_wedge_never_resets_failure_count(tmp_path):
    """EXIT_WEDGED must not reset the failure counter, however long the
    child lived: a wedged child's lifetime includes the whole watchdog
    timeout spent hung. --healthy-secs 0 makes every exit 'healthy' by
    wall clock — with the reset applying to wedges this loops forever."""
    cmd = [
        sys.executable, SUPERVISOR, "--max-restarts", "2",
        "--backoff-base", "0.05", "--healthy-secs", "0", "--",
        sys.executable, "-c", f"import sys; sys.exit({EXIT_WEDGED})",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert proc.returncode == EXIT_WEDGED
    sup = [
        json.loads(l) for l in proc.stdout.splitlines()
        if l.startswith('{"supervisor"')
    ]
    events = [e["event"] for e in sup]
    assert events.count("launch") == 3  # 1 + 2 restarts, then give up
    assert "failure_count_reset" not in events


def test_supervisor_forwards_sigterm_and_does_not_relaunch(tmp_path):
    """A TERM delivered to the supervisor ALONE must reach the child (no
    orphan) and surface the child's exit code without a relaunch."""
    import signal as _signal

    ready = tmp_path / "ready"
    child = (
        "import pathlib, signal, sys, time; "
        "signal.signal(signal.SIGTERM, lambda *a: sys.exit(43)); "
        f"pathlib.Path({str(ready)!r}).write_text('r'); "
        "time.sleep(120)"
    )
    cmd = [
        sys.executable, SUPERVISOR, "--backoff-base", "0.05", "--",
        sys.executable, "-c", child,
    ]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 30.0
        while not ready.exists() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert ready.exists(), "child never came up under the supervisor"
        os.kill(proc.pid, _signal.SIGTERM)  # supervisor only, not the group
        out, _ = proc.communicate(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 43  # the child's EXIT_PREEMPTED, surfaced
    sup = [json.loads(l) for l in out.splitlines() if l.startswith('{"supervisor"')]
    events = [e["event"] for e in sup]
    assert events.count("launch") == 1  # terminated supervisors don't relaunch
    assert "terminated" in events
