"""Ring attention on a real (virtual) seq-sharded mesh vs dense attention."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.ops.attention import naive_attention
from pretraining_llm_tpu.parallel.ring_attention import ring_attention
from pretraining_llm_tpu.parallel.sharding import activation_mesh
from pretraining_llm_tpu.training import train_step as ts


def _qkv(key, b=2, t=64, h=2, dh=16):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, dh), jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(mesh_seq4, causal):
    q, k, v = _qkv(jax.random.key(0))
    want = naive_attention(q, k, v, causal=causal)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh_seq4, causal=causal)

    got = run(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_ring_gradients_match_dense(mesh_seq4):
    q, k, v = _qkv(jax.random.key(1), t=32)

    def loss_dense(q, k, v):
        return jnp.sum(naive_attention(q, k, v) ** 2)

    @jax.jit
    def loss_ring_grad(q, k, v):
        return jax.grad(lambda *a: jnp.sum(ring_attention(*a, mesh_seq4) ** 2), (0, 1, 2))(
            q, k, v
        )

    g_dense = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    g_ring = loss_ring_grad(q, k, v)
    for a, b in zip(g_dense, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_ring_with_sharded_inputs(mesh_seq4):
    """Inputs already laid out seq-sharded on device: no resharding surprises."""
    q, k, v = _qkv(jax.random.key(2), b=2, t=128)
    sharding = NamedSharding(mesh_seq4, P(("data",), "seq", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh_seq4)

    got = run(qs, ks, vs)
    want = naive_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_seq_parallel_train_step_matches_dense(mesh_seq4):
    """Full train step with attention_impl='ring' + sequence_parallel on a
    seq=4 mesh == the same step with dense attention on a single device."""
    cfg = get_preset("tiny").with_overrides(
        {
            "model.compute_dtype": "float32",
            "model.attention_impl": "ring",
            "model.sequence_parallel": True,
            "train.batch_size": 4,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
        }
    )
    cfg_dense = cfg.with_overrides(
        {"model.attention_impl": "naive", "model.sequence_parallel": False}
    )

    state_ring = ts.init_train_state(cfg, jax.random.key(0))
    state_dense = ts.init_train_state(cfg_dense, jax.random.key(0))
    step_ring = ts.build_train_step(cfg, mesh=mesh_seq4)
    step_dense = ts.build_train_step(cfg_dense, mesh=None)
    state_ring = ts.shard_train_state(state_ring, mesh_seq4)

    x = jax.random.randint(jax.random.key(1), (4, cfg.model.context_length), 0, cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)
    for _ in range(2):
        state_ring, mr = step_ring(state_ring, (x, y))
        state_dense, md = step_dense(state_dense, (x, y))
    np.testing.assert_allclose(float(mr["loss"]), float(md["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4
        ),
        state_ring["params"],
        state_dense["params"],
    )


def test_zigzag_perm_structure():
    from pretraining_llm_tpu.parallel.zigzag import inverse_perm, zigzag_perm

    perm = zigzag_perm(64, 4)
    assert sorted(perm.tolist()) == list(range(64))
    # Device i's shard = chunks (i, 2n-1-i): device 0 holds chunks 0 and 7.
    c = 64 // 8
    assert perm[:c].tolist() == list(range(0, c))
    assert perm[c : 2 * c].tolist() == list(range(7 * c, 8 * c))
    inv = inverse_perm(perm)
    assert (perm[inv] == np.arange(64)).all()


def test_ring_zigzag_matches_dense(mesh_seq4):
    """Zigzag layout: ring on permuted inputs + position-aware dense agree."""
    from pretraining_llm_tpu.parallel.zigzag import zigzag_perm

    q, k, v = _qkv(jax.random.key(4), t=64)
    perm = zigzag_perm(64, 4)
    qp, kp, vp = (x[:, perm] for x in (q, k, v))
    pos = jnp.asarray(perm)
    want = naive_attention(qp, kp, vp, causal=True, q_positions=pos, kv_positions=pos)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh_seq4, causal=True, layout="zigzag")

    got = run(qp, kp, vp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
    # Equivalently: un-permuting the zigzag output reproduces plain dense.
    inv = np.argsort(perm)
    np.testing.assert_allclose(
        np.asarray(got)[:, inv], np.asarray(naive_attention(q, k, v)), rtol=1e-5, atol=1e-5
    )


def test_ring_zigzag_gradients_match_dense(mesh_seq4):
    from pretraining_llm_tpu.parallel.zigzag import zigzag_perm

    q, k, v = _qkv(jax.random.key(5), t=32)
    perm = zigzag_perm(32, 4)
    pos = jnp.asarray(perm)
    qp, kp, vp = (x[:, perm] for x in (q, k, v))

    def loss_dense(q, k, v):
        return jnp.sum(
            naive_attention(q, k, v, q_positions=pos, kv_positions=pos) ** 2
        )

    @jax.jit
    def grad_ring(q, k, v):
        return jax.grad(
            lambda *a: jnp.sum(ring_attention(*a, mesh_seq4, layout="zigzag") ** 2),
            (0, 1, 2),
        )(q, k, v)

    g_dense = jax.grad(loss_dense, (0, 1, 2))(qp, kp, vp)
    g_ring = grad_ring(qp, kp, vp)
    for a, b in zip(g_dense, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_ring_degrades_to_naive_off_mesh():
    """impl='ring' without a seq mesh must run the dense path (same numbers)."""
    from pretraining_llm_tpu.ops.attention import multihead_attention

    q, k, v = _qkv(jax.random.key(3))
    with activation_mesh(None):
        got = multihead_attention(q, k, v, impl="ring")
    want = naive_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


@pytest.mark.parametrize("layout", ["contiguous", "zigzag"])
def test_ring_gqa_matches_grouped_dense(mesh_seq4, layout):
    """Grouped-query ring: G KV heads rotate (G/H the ppermute bytes), output
    matches the grouped naive path. Zigzag needs the caller's permutation —
    here we compare ring-on-permuted vs dense-on-permuted with matching
    position semantics (causal over the PERMUTED order is only equivalent
    chunk-wise, so zigzag is exercised non-causally)."""
    b, t, h, g, dh = 2, 64, 8, 2, 16
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (b, t, h, dh), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, g, dh), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, g, dh), jnp.float32)
    causal = layout == "contiguous"
    want = naive_attention(q, k, v, causal=causal)

    @jax.jit
    def run(q, k, v):
        return ring_attention(q, k, v, mesh_seq4, causal=causal, layout=layout)

    got = run(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_ring_gqa_gradients_match_grouped_dense(mesh_seq4):
    b, t, h, g, dh = 2, 32, 4, 2, 8
    ks = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(ks[0], (b, t, h, dh), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, g, dh), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, g, dh), jnp.float32)

    g_dense = jax.grad(lambda *a: jnp.sum(naive_attention(*a) ** 2), (0, 1, 2))(q, k, v)

    @jax.jit
    def ring_grads(q, k, v):
        return jax.grad(
            lambda *a: jnp.sum(ring_attention(*a, mesh_seq4) ** 2), (0, 1, 2)
        )(q, k, v)

    for a, b_ in zip(g_dense, ring_grads(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5)


def test_ring_gqa_rejects_indivisible_heads(mesh_seq4):
    q, k, v = _qkv(jax.random.key(5), h=4)
    with pytest.raises(ValueError, match="must divide"):
        ring_attention(q, k[:, :, :3], v[:, :, :3], mesh_seq4)


def test_seq_parallel_gqa_train_step_matches_dense(mesh_seq4):
    """GQA model (n_kv_heads < n_heads) through ring + zigzag + SP: the
    grouped KV rotates the ring un-expanded and the step still matches the
    single-device dense run."""
    cfg = get_preset("tiny").with_overrides(
        {
            "model.compute_dtype": "float32",
            "model.n_heads": 4,
            "model.n_kv_heads": 2,
            "model.attention_impl": "ring",
            "model.sequence_parallel": True,
            "train.batch_size": 4,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
        }
    )
    cfg_dense = cfg.with_overrides(
        {"model.attention_impl": "naive", "model.sequence_parallel": False}
    )

    state_ring = ts.init_train_state(cfg, jax.random.key(0))
    state_dense = ts.init_train_state(cfg_dense, jax.random.key(0))
    step_ring = ts.build_train_step(cfg, mesh=mesh_seq4)
    step_dense = ts.build_train_step(cfg_dense, mesh=None)
    state_ring = ts.shard_train_state(state_ring, mesh_seq4)

    x = jax.random.randint(
        jax.random.key(1), (4, cfg.model.context_length), 0, cfg.model.vocab_size
    )
    y = jnp.roll(x, -1, axis=1)
    for _ in range(2):
        state_ring, mr = step_ring(state_ring, (x, y))
        state_dense, md = step_dense(state_dense, (x, y))
    np.testing.assert_allclose(float(mr["loss"]), float(md["loss"]), rtol=1e-5)
