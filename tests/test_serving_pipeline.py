"""Deep-pipelined serving scheduler: depth-N in-flight window queue.

The correctness bar (CPU-enforced): greedy tokens are BIT-IDENTICAL to
the synchronous scheduler (`run(pipeline=False)`) at EVERY pipeline
depth, through admission churn, early finishes, stop tokens, and the
preemption/replay reconciliation path. The pipelining is pure host
scheduling — a depth that changed a single emitted token would be a
speculation-reconciliation bug, not a perf trade-off.
"""

import dataclasses

import jax
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.serving import ServingEngine
from pretraining_llm_tpu.models import transformer

import jax.numpy as jnp

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
DRAFT_CFG = dataclasses.replace(CFG, n_layers=1, d_model=16, n_heads=2)

DEPTHS = [1, 2, 3]


@pytest.fixture(scope="module")
def params():
    return transformer.init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def draft_params():
    return transformer.init_params(DRAFT_CFG, jax.random.key(99))


def _prompts(n, lengths=(5, 9, 14, 7, 11, 3, 16, 6)):
    rng = np.random.default_rng(42)
    out = []
    for i in range(n):
        p = int(lengths[i % len(lengths)])
        out.append(rng.integers(0, CFG.vocab_size, size=p).tolist())
    return out


def _reference_greedy(params, cfg, prompt, n_new):
    toks = generate(
        params, cfg, jnp.asarray([prompt], jnp.int32), n_new,
        jax.random.key(7), temperature=0.0,
    )
    return np.asarray(toks)[0].tolist()


def _run_pair(params, prompts, n_new, *, depth, **kw):
    """Run the SAME workload through the synchronous scheduler and the
    pipelined one at ``depth``; returns (sync_out, piped_out, piped_eng).
    Two engines: run() mutates allocator/pool state."""
    sync = ServingEngine(params, CFG, temperature=0.0, **kw)
    s_rids = [sync.submit(p, n_new) for p in prompts]
    s_out = sync.run(pipeline=False)
    piped = ServingEngine(
        params, CFG, temperature=0.0, pipeline_depth=depth, **kw
    )
    p_rids = [piped.submit(p, n_new) for p in prompts]
    p_out = piped.run(pipeline=True)
    assert s_rids == p_rids  # same submission order -> same rids
    return s_out, p_out, piped


# -- bit-identity at every depth ------------------------------------------


@pytest.mark.parametrize("depth", DEPTHS)
def test_depth_identity_admission_churn(params, depth):
    """More requests than rows: rows free and re-admit continuously, so
    windows carry surplus tokens for finished rows and admission merges
    land mid-queue — tokens must not move by one bit at any depth."""
    prompts = _prompts(6)
    n_new = 9  # not a multiple of the window: mid-window finishes
    s_out, p_out, eng = _run_pair(
        params, prompts, n_new, depth=depth,
        max_batch=2, n_blocks=24, block_size=8, steps_per_sched=4,
    )
    assert p_out == s_out
    for rid, p in zip(sorted(p_out), prompts):
        assert p_out[rid] == _reference_greedy(params, CFG, p, n_new)
    assert eng.stats["windows_reaped"] == eng.stats["windows"]


@pytest.mark.parametrize("depth", DEPTHS)
def test_depth_identity_early_finish_stop_token(params, depth):
    """A stop token landing mid-window finishes rows early while deeper
    queues keep dispatching surplus windows for them — the surplus must
    be discarded at reap, never emitted."""
    prompts = _prompts(3)
    n_new = 12
    refs = [_reference_greedy(params, CFG, p, n_new) for p in prompts]
    stop = refs[0][4]  # a token greedy WILL emit for prompt 0
    s_out, p_out, _ = _run_pair(
        params, prompts, n_new, depth=depth,
        max_batch=2, n_blocks=32, block_size=8, steps_per_sched=4,
        stop_token=stop,
    )
    assert p_out == s_out
    for rid, ref in zip(sorted(p_out), refs):
        want = ref[: ref.index(stop)] if stop in ref else ref
        assert p_out[rid] == want


@pytest.mark.parametrize("depth", DEPTHS)
def test_depth_identity_preemption_replay(params, depth):
    """Tiny pool forcing preemption: the queue must FLUSH before any
    eviction decision (committed prompt+generated bookkeeping), then
    replay from committed state — recompute-on-resume resumes from the
    exact prefix at every depth."""
    prompts = [_prompts(1, lengths=(12,))[0], _prompts(1, lengths=(10,))[0]]
    n_new = 24
    s_out, p_out, eng = _run_pair(
        params, prompts, n_new, depth=depth,
        max_batch=2, n_blocks=8, block_size=8, steps_per_sched=4,
    )
    assert p_out == s_out
    assert eng.stats["preemptions"] >= 1
    for rid, p in zip(sorted(p_out), prompts):
        assert p_out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_replay_path_flushes_inflight_queue(params):
    """The reconciliation path itself: with a deep queue and a pool too
    small for the in-flight horizon, a dry allocator must drain the
    queue (stats['flushes']), invalidate the speculative chain, and the
    next dispatch must restart from committed host state — outputs still
    exact. This is the test that fails if _flush_inflight or the
    empty-queue replay branch of _dispatch_window regresses."""
    prompts = [_prompts(1, lengths=(12,))[0], _prompts(1, lengths=(10,))[0]]
    n_new = 24
    s_out, p_out, eng = _run_pair(
        params, prompts, n_new, depth=3,
        max_batch=2, n_blocks=8, block_size=8, steps_per_sched=4,
    )
    assert p_out == s_out
    assert eng.stats["flushes"] >= 1, eng.stats
    # Every dispatched window is accounted for despite the flushes.
    assert eng.stats["windows_reaped"] == eng.stats["windows"]


@pytest.mark.parametrize("depth", [2, 3])
def test_depth_identity_max_new_one(params, depth):
    """max_new=1 finishes on the deferred admission token alone — the
    row must free and recycle without ever joining a decode window."""
    prompts = _prompts(3)
    s_out, p_out, _ = _run_pair(
        params, prompts, 1, depth=depth,
        max_batch=1, n_blocks=16, block_size=8, steps_per_sched=4,
    )
    assert p_out == s_out
    for rid, p in zip(sorted(p_out), prompts):
        assert p_out[rid] == _reference_greedy(params, CFG, p, 1)


# -- speculative rounds join the queue ------------------------------------


@pytest.mark.parametrize("depth", [2, 3])
def test_spec_rounds_join_queue_identity(params, draft_params, depth):
    """Speculative serving at depth > 1: round k+1 chains seed+frontier
    on device (spec_next_inputs) while round k is unreaped. Greedy
    output must equal the synchronous spec scheduler AND the dense-cache
    target-only reference, with an untrained low-hit-rate draft."""
    prompts = _prompts(4)
    n_new = 10
    s_out, p_out, eng = _run_pair(
        params, prompts, n_new, depth=depth,
        max_batch=2, n_blocks=32, block_size=8,
        draft_params=draft_params, draft_cfg=DRAFT_CFG, spec_k=3,
    )
    assert p_out == s_out
    assert eng.stats["spec_rounds"] > 0
    for rid, p in zip(sorted(p_out), prompts):
        assert p_out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_spec_pipelined_self_draft_acceptance_accounting(params):
    """Self-draft at depth 2: acceptance must still be total, and the
    reap-time telemetry must count only SURVIVING rows' rounds — surplus
    rounds for finished rows skew neither proposed nor accepted."""
    p = _prompts(1)[0]
    n_new = 9
    eng = ServingEngine(
        params, CFG, max_batch=1, n_blocks=32, block_size=8,
        temperature=0.0, draft_params=params, draft_cfg=CFG, spec_k=2,
        pipeline_depth=2,
    )
    rid = eng.submit(p, n_new)
    out = eng.run(pipeline=True)
    assert out[rid] == _reference_greedy(params, CFG, p, n_new)
    st = eng.stats
    assert st["spec_accepted"] == st["spec_proposed"], st


# -- cross-window admission batching --------------------------------------


def test_admit_batch_defers_then_batches(params):
    """admit_batch=3 with one row initially free: the gate must DEFER
    dribble admissions until three can land in one batched prefill, and
    the deferral must not change a single emitted token."""
    prompts = _prompts(6)
    n_new = 8
    kw = dict(max_batch=4, n_blocks=48, block_size=8, steps_per_sched=4)
    s_out, p_out, eng = _run_pair(
        params, prompts, n_new, depth=2, admit_batch=3, **kw
    )
    assert p_out == s_out
    assert eng.stats.get("admit_batches", 0) + eng.stats.get(
        "admit_deferrals", 0) >= 1, eng.stats
    for rid, p in zip(sorted(p_out), prompts):
        assert p_out[rid] == _reference_greedy(params, CFG, p, n_new)


def test_admit_batch_idle_engine_never_deadlocks(params):
    """An idle engine (no active rows) must admit whatever fits even if
    fewer than admit_batch requests are waiting — the gate only defers
    while the device has other work."""
    prompts = _prompts(2)
    n_new = 6
    eng = ServingEngine(
        params, CFG, max_batch=4, n_blocks=32, block_size=8,
        temperature=0.0, pipeline_depth=2, admit_batch=8,
    )
    rids = [eng.submit(p, n_new) for p in prompts]
    out = eng.run(pipeline=True)
    for rid, p in zip(rids, prompts):
        assert out[rid] == _reference_greedy(params, CFG, p, n_new)


# -- host-blocked telemetry -----------------------------------------------


def test_host_blocked_counter_monotonic(params, monkeypatch):
    """Per-reap telemetry invariants: windows_reaped increments by
    exactly one per reap and host_blocked_s is monotonically
    non-decreasing (a reap that SUBTRACTED blocked time would corrupt
    the per-window average bench.py reports)."""
    seen = []
    orig = ServingEngine._reap_window

    def spy(self, w):
        orig(self, w)
        seen.append(
            (self.stats["windows_reaped"], self.stats["host_blocked_s"])
        )

    monkeypatch.setattr(ServingEngine, "_reap_window", spy)
    prompts = _prompts(4)
    eng = ServingEngine(
        params, CFG, max_batch=2, n_blocks=32, block_size=8,
        temperature=0.0, steps_per_sched=4, pipeline_depth=2,
    )
    for p in prompts:
        eng.submit(p, 8)
    eng.run(pipeline=True)
    assert len(seen) >= 2
    assert [n for n, _ in seen] == list(range(1, len(seen) + 1))
    blocked = [b for _, b in seen]
    assert all(b2 >= b1 >= 0.0 for b1, b2 in zip(blocked, blocked[1:]))
    assert eng.stats["host_blocked_s"] == blocked[-1]


def test_reap_window_records_spans(params):
    """Each dispatch/reap lands a span with the per-window host-blocked
    seconds in its meta — the counters the Chrome trace exposes."""
    from pretraining_llm_tpu.observability import spans

    rec = spans.SpanRecorder()
    spans.set_recorder(rec)
    try:
        eng = ServingEngine(
            params, CFG, max_batch=2, n_blocks=32, block_size=8,
            temperature=0.0, steps_per_sched=4, pipeline_depth=2,
        )
        for p in _prompts(2):
            eng.submit(p, 6)
        eng.run(pipeline=True)
        summary = rec.summary()
        assert summary["serving.dispatch_window"]["count"] == eng.stats["windows"]
        assert summary["serving.reap_window"]["count"] == eng.stats["windows_reaped"]
        trace = rec.to_chrome_trace()["traceEvents"]
        reaps = [e for e in trace if e["name"] == "serving.reap_window"]
        assert reaps and all(
            "host_blocked_s" in e["args"] and e["args"]["host_blocked_s"] >= 0
            for e in reaps
        )
    finally:
        spans.set_recorder(spans.SpanRecorder())


# -- engine knob validation ------------------------------------------------


def test_pipeline_knob_validation(params):
    with pytest.raises(ValueError, match="pipeline_depth"):
        ServingEngine(params, CFG, pipeline_depth=0)
    with pytest.raises(ValueError, match="admit_batch"):
        ServingEngine(params, CFG, admit_batch=-1)


def test_depth_one_is_double_buffered_scheduler(params):
    """depth=1 must reproduce the classic double-buffered scheduler:
    never more than one unreaped window beyond the reap threshold, and
    outputs identical to sync (the degenerate case of the depth
    contract)."""
    prompts = _prompts(4)
    n_new = 8
    s_out, p_out, eng = _run_pair(
        params, prompts, n_new, depth=1,
        max_batch=2, n_blocks=32, block_size=8, steps_per_sched=4,
    )
    assert p_out == s_out
    assert eng.pipeline_depth == 1
    assert eng.stats["windows_reaped"] == eng.stats["windows"]
