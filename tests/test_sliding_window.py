"""Sliding-window attention (Mistral-style, model.sliding_window).

Each query attends only the last `window` positions. The flash kernel skips
blocks entirely below the window (O(T*window) compute); cached decode masks
old slots rather than evicting them.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.models import transformer
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.ops.attention import naive_attention
from pretraining_llm_tpu.ops.flash_attention import blockwise_attention
from pretraining_llm_tpu.ops.pallas_flash import pallas_flash_attention


def _ref(q, k, v, window, seg=None):
    b, t, h, d = q.shape
    g = k.shape[2]
    kr = jnp.repeat(k, h // g, axis=2)
    vr = jnp.repeat(v, h // g, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kr) / d**0.5
    qp = jnp.arange(t)[:, None]
    kp = jnp.arange(t)[None, :]
    mask = (qp >= kp) & (qp - kp < window)
    mask = jnp.broadcast_to(mask[None, None], s.shape)
    if seg is not None:
        mask = mask & (seg[:, None, :, None] == seg[:, None, None, :])
    s = jnp.where(mask, s, -jnp.inf)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), vr)


@pytest.fixture(scope="module")
def qkv():
    b, t, h, g, d = 2, 256, 4, 2, 32
    q = jax.random.normal(jax.random.key(1), (b, t, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (b, t, g, d), jnp.float32)
    v = jax.random.normal(jax.random.key(3), (b, t, g, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("window", [1, 50, 64, 200])
def test_naive_window_matches_reference(qkv, window):
    q, k, v = qkv
    got = naive_attention(q, k, v, window=window)
    np.testing.assert_allclose(got, _ref(q, k, v, window), atol=2e-5)


@pytest.mark.parametrize("window", [50, 64, 200])
def test_blockwise_window_matches_reference(qkv, window):
    q, k, v = qkv
    got = blockwise_attention(q, k, v, window=window, block_q=64, block_kv=64)
    np.testing.assert_allclose(got, _ref(q, k, v, window), atol=2e-5)


@pytest.mark.parametrize("window,blocks", [
    (50, (64, 64)),   # window < block: early blocks fully masked per row
    (64, (64, 64)),   # window == block
    (200, (128, 64)), # window spans blocks
    (50, (0, 0)),     # single block -> fused backward path
])
def test_pallas_window_matches_reference_fwd_and_grad(qkv, window, blocks):
    q, k, v = qkv
    bq, bk = blocks

    def kern(q, k, v):
        return pallas_flash_attention(
            q, k, v, window=window, block_q=bq, block_kv=bk, interpret=True
        )

    np.testing.assert_allclose(kern(q, k, v), _ref(q, k, v, window), atol=2e-5)
    gk = jax.grad(lambda *a: (kern(*a) ** 2).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: (_ref(*a, window) ** 2).sum(), (0, 1, 2))(q, k, v)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_pallas_window_composes_with_segments(qkv):
    q, k, v = qkv
    t = q.shape[1]
    seg = jnp.stack([
        jnp.where(jnp.arange(t) < 100, 0, 1),
        jnp.where(jnp.arange(t) < 40, 0, 1),
    ]).astype(jnp.int32)
    got = pallas_flash_attention(
        q, k, v, window=70, segments=seg, block_q=64, block_kv=64,
        interpret=True,
    )
    np.testing.assert_allclose(got, _ref(q, k, v, 70, seg), atol=2e-5)


def test_model_flash_equals_naive_with_window():
    logits = {}
    toks = None
    for impl in ("naive", "flash"):
        cfg = dataclasses.replace(
            get_preset("tiny").model,
            compute_dtype="float32",
            attention_impl=impl,
            sliding_window=10,
        )
        params = transformer.init_params(cfg, jax.random.key(0))
        if toks is None:
            toks = jax.random.randint(
                jax.random.key(4), (2, cfg.context_length), 0, cfg.vocab_size
            )
        logits[impl], _ = transformer.forward(params, toks, cfg)
    np.testing.assert_allclose(
        logits["naive"], logits["flash"], atol=2e-4, rtol=1e-4
    )


def test_model_window_limits_receptive_field():
    """With window W, position p's logits depend only on tokens in
    (p - W, p] — rewriting older tokens changes nothing."""
    cfg = dataclasses.replace(
        get_preset("tiny").model, compute_dtype="float32", sliding_window=8
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    t = cfg.context_length
    a = jax.random.randint(jax.random.key(5), (1, t), 0, cfg.vocab_size)
    # NOTE the receptive field COMPOUNDS across layers (each layer sees W
    # back, so depth L sees ~L*W back) — probe the last position with a
    # rewrite strictly older than n_layers * window.
    reach = cfg.n_layers * cfg.sliding_window
    assert t > reach + 4, "tiny preset too short for this probe"
    b = a.at[0, : t - reach - 1].set(
        jax.random.randint(jax.random.key(6), (t - reach - 1,), 0, cfg.vocab_size)
    )
    la, _ = transformer.forward(params, a, cfg)
    lb, _ = transformer.forward(params, b, cfg)
    np.testing.assert_array_equal(
        np.asarray(la[0, -1]), np.asarray(lb[0, -1])
    )
    # Sanity: full attention DOES leak from the distant prefix.
    cfg_full = dataclasses.replace(cfg, sliding_window=0)
    la_f, _ = transformer.forward(params, a, cfg_full)
    lb_f, _ = transformer.forward(params, b, cfg_full)
    assert float(jnp.abs(la_f[0, -1] - lb_f[0, -1]).max()) > 1e-4


def test_window_cached_greedy_decode_matches_uncached():
    """KV-cached decode with a sliding window == argmax over full
    re-forwards of the SAME windowed model (old cache slots are masked,
    not evicted)."""
    cfg = dataclasses.replace(
        get_preset("tiny").model, compute_dtype="float32", sliding_window=6
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(7), (1, 8), 0, cfg.vocab_size)
    n_new = 10
    got = np.asarray(
        generate(params, cfg, prompt, n_new, jax.random.key(8), temperature=0.0)
    )
    seq = np.asarray(prompt)
    for _ in range(n_new):
        logits, _ = transformer.forward(params, jnp.asarray(seq), cfg)
        seq = np.concatenate([seq, [[int(jnp.argmax(logits[0, -1]))]]], axis=1)
    np.testing.assert_array_equal(got, seq[:, 8:])


def test_window_validation():
    with pytest.raises(ValueError, match="ring/ulysses"):
        ModelConfig(attention_impl="ring", sliding_window=128)
    with pytest.raises(ValueError, match=">= 0"):
        ModelConfig(sliding_window=-1)


def test_window_chunked_prefill_matches_full_forward():
    """Chunked windowed prefill trims the below-window cache prefix
    (tile-aligned, k_offset keeps positions absolute) and must still track
    the full-sequence windowed forward."""
    cfg = dataclasses.replace(
        get_preset("tiny").model,
        compute_dtype="float32",
        attention_impl="flash",
        pos_embed="rope",
        sliding_window=6,
        # tiny tile so the low-side slice actually engages at T=24
        flash_block_kv=4,
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(7), (2, 24), 0, cfg.vocab_size)
    full, _ = transformer.forward(params, tokens, cfg)

    cache = transformer.make_kv_cache(cfg, 2, 24, dtype="float32")
    got = []
    for start in (0, 8, 16):
        logits, cache = transformer.forward(
            params, tokens[:, start : start + 8], cfg, kv_cache=cache,
            cache_index=jnp.int32(start),
        )
        got.append(logits)
    got = jnp.concatenate(got, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), rtol=2e-4, atol=2e-4
    )
