"""Speculative decoding: greedy losslessness, acceptance telemetry, guards."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.generation.generate import generate
from pretraining_llm_tpu.generation.speculative import generate_speculative
from pretraining_llm_tpu.models import transformer

CFG = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")


@pytest.fixture(scope="module")
def target_params():
    return transformer.init_params(CFG, jax.random.key(0))


@pytest.fixture(scope="module")
def draft_setup():
    """A genuinely different (smaller) draft model."""
    cfg_d = dataclasses.replace(CFG, n_layers=1, d_model=32, n_heads=2)
    return cfg_d, transformer.init_params(cfg_d, jax.random.key(9))


@pytest.mark.parametrize("k", [1, 3, 5])
def test_greedy_speculative_equals_target_greedy(target_params, draft_setup, k):
    """The load-bearing contract: greedy speculative output == target-only
    greedy decode, for a draft that actually disagrees with the target."""
    cfg_d, draft_params = draft_setup
    prompt = jax.random.randint(jax.random.key(1), (1, 8), 0, CFG.vocab_size)
    n_new = 12
    want = np.asarray(
        generate(target_params, CFG, prompt, n_new, jax.random.key(2),
                 temperature=0.0)
    )[0]
    got, stats = generate_speculative(
        target_params, CFG, draft_params, cfg_d, prompt, n_new,
        jax.random.key(3), k=k, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["rounds"] >= 1
    assert 0 <= stats["accepted"] <= stats["proposed"]


def test_self_draft_accepts_everything(target_params):
    """Draft == target: every greedy proposal is accepted, so the loop
    finishes in ~max_new/(k+1) rounds and the output still matches."""
    prompt = jax.random.randint(jax.random.key(4), (1, 6), 0, CFG.vocab_size)
    n_new, k = 12, 3
    want = np.asarray(
        generate(target_params, CFG, prompt, n_new, jax.random.key(5),
                 temperature=0.0)
    )[0]
    got, stats = generate_speculative(
        target_params, CFG, target_params, CFG, prompt, n_new,
        jax.random.key(6), k=k, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["accepted"] == stats["proposed"]
    # ceil((n_new - 1) / (k + 1)) rounds when everything is accepted
    assert stats["rounds"] == -(-(n_new - 1) // (k + 1))


def test_sampling_mode_produces_valid_tokens(target_params, draft_setup):
    cfg_d, draft_params = draft_setup
    prompt = jax.random.randint(jax.random.key(7), (1, 5), 0, CFG.vocab_size)
    got, stats = generate_speculative(
        target_params, CFG, draft_params, cfg_d, prompt, 10,
        jax.random.key(8), k=4, temperature=1.0,
    )
    got = np.asarray(got)
    assert got.shape == (10,)
    assert ((got >= 0) & (got < CFG.vocab_size)).all()
    assert stats["proposed"] == stats["rounds"] * 4


def test_speculative_guards(target_params, draft_setup):
    cfg_d, draft_params = draft_setup
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="vocab"):
        generate_speculative(
            target_params, CFG, draft_params,
            dataclasses.replace(cfg_d, vocab_size=CFG.vocab_size + 1),
            prompt, 4, jax.random.key(0),
        )
    with pytest.raises(ValueError, match="batch-1"):
        generate_speculative(
            target_params, CFG, draft_params, cfg_d,
            jnp.zeros((2, 4), jnp.int32), 4, jax.random.key(0),
        )
    with pytest.raises(ValueError, match="context"):
        generate_speculative(
            target_params, CFG, draft_params, cfg_d, prompt,
            CFG.context_length, jax.random.key(0),
        )
    with pytest.raises(ValueError, match="k must be"):
        generate_speculative(
            target_params, CFG, draft_params, cfg_d, prompt, 4,
            jax.random.key(0), k=0,
        )


def test_greedy_speculative_with_flash_target(target_params, draft_setup):
    """The verify forward (k+1 tokens at a traced offset) routes through
    the chunked-blockwise path under attention_impl=flash and must agree
    with the naive result."""
    cfg_d, draft_params = draft_setup
    cfg_flash = dataclasses.replace(CFG, attention_impl="flash")
    prompt = jax.random.randint(jax.random.key(10), (1, 8), 0, CFG.vocab_size)
    want, _ = generate_speculative(
        target_params, CFG, draft_params, cfg_d, prompt, 8,
        jax.random.key(11), k=3, temperature=0.0,
    )
    got, _ = generate_speculative(
        target_params, cfg_flash, draft_params, cfg_d, prompt, 8,
        jax.random.key(11), k=3, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_text_speculative_cli_path(tmp_path):
    """End-to-end through checkpoints + tokenizer: the speculative text API
    produces the same continuation as the plain greedy CLI path."""
    import dataclasses as dc

    from pretraining_llm_tpu.config import Config, DataConfig, get_preset
    from pretraining_llm_tpu.generation.generate import (
        generate_text, generate_text_speculative,
    )
    from pretraining_llm_tpu.training import checkpoint as ckpt

    def save(cfg_model, seed, path):
        cfg = Config(model=cfg_model,
                     data=DataConfig(tokenizer_name="byte"), name="t")
        params = transformer.init_params(cfg_model, jax.random.key(seed))
        params = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
        ckpt.save_checkpoint(
            str(path), 0, {"params": params},
            extra={"step": 0, "config": dc.asdict(cfg), "preset": "t"},
        )

    target_cfg = dc.replace(CFG, vocab_size=256, compute_dtype="float32")
    draft_cfg = dc.replace(target_cfg, n_layers=1, d_model=32, n_heads=2)
    save(target_cfg, 0, tmp_path / "target")
    save(draft_cfg, 9, tmp_path / "draft")

    want = generate_text(
        str(tmp_path / "target"), "hello", 8, temperature=0.0,
    )
    got = generate_text_speculative(
        str(tmp_path / "target"), str(tmp_path / "draft"), "hello", 8,
        k=3, temperature=0.0,
    )
    assert got == want
