"""In-repo tokenizers: byte fallback and trainable BPE."""

import pytest

from pretraining_llm_tpu.data.bpe import BPETokenizer, ByteTokenizer
from pretraining_llm_tpu.data.tokenizer import get_tokenizer


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "Hello, TPU world! éàü"
    ids = tok.encode_ordinary(text)
    assert all(0 <= i < 256 for i in ids)
    assert tok.decode(ids) == text
    assert tok.eot_token == 256
    assert tok.n_vocab == 257


def test_bpe_train_and_roundtrip():
    corpus = ["the quick brown fox jumps over the lazy dog " * 20,
              "the quick red fox runs over the sleepy cat " * 20]
    tok = BPETokenizer.train(corpus, vocab_size=300)
    assert 257 <= tok.n_vocab <= 300
    text = "the quick fox"
    ids = tok.encode_ordinary(text)
    assert tok.decode(ids) == text
    # Merges actually compress: fewer tokens than bytes.
    assert len(ids) < len(text.encode())


def test_bpe_save_load(tmp_path):
    corpus = ["aaa bbb aaa bbb aaa bbb " * 30]
    tok = BPETokenizer.train(corpus, vocab_size=280)
    path = str(tmp_path / "bpe.json")
    tok.save(path)
    tok2 = BPETokenizer.load(path)
    text = "aaa bbb"
    assert tok.encode_ordinary(text) == tok2.encode_ordinary(text)
    assert tok2.decode(tok2.encode_ordinary(text)) == text
    # get_tokenizer dispatches on .json path
    tok3 = get_tokenizer(path)
    assert tok3.encode_ordinary(text) == tok.encode_ordinary(text)


def test_bpe_handles_unseen_bytes():
    tok = BPETokenizer.train(["abc abc abc " * 10], vocab_size=270)
    text = "xyz ☃"  # snowman: multibyte UTF-8 never seen in training
    assert tok.decode(tok.encode_ordinary(text)) == text


def test_get_tokenizer_byte_and_unknown():
    assert get_tokenizer("byte").n_vocab == 257
    with pytest.raises(ValueError):
        get_tokenizer("nonsense")


def test_native_bpe_matches_python_sweep():
    """The C++ encoder (native/bpe.cpp) must be bit-identical to the Python
    greedy sweep — same lowest-rank-first, leftmost-first merge order."""
    import random

    from pretraining_llm_tpu.data import native_bpe
    from pretraining_llm_tpu.data.bpe import BPETokenizer

    if not native_bpe.native_available():
        import pytest

        pytest.skip("no C++ toolchain to build libbpe.so")

    corpus = [
        "the quick brown fox jumps over the lazy dog " * 20,
        "hello hello hello world world " * 30,
        "aaaa bbbb aaaa bbbb abab " * 25,
    ]
    tok = BPETokenizer.train(corpus, vocab_size=300)
    enc = native_bpe.NativeBpeEncoder(tok.merges)

    rng = random.Random(0)
    samples = corpus + [
        "",
        "a",
        "aaaaaaaa",
        "the the the",
        "éèê unicode café naïve",  # multi-byte UTF-8
        "".join(rng.choice("abcdefgh \n\t") for _ in range(2000)),
        "".join(chr(rng.randrange(32, 1000)) for _ in range(500)),
    ]
    for text in samples:
        byte_ids = list(text.encode("utf-8"))
        want = tok._encode_python(list(byte_ids))
        got = enc.encode_bytes(text.encode("utf-8"))
        assert got == want, f"native != python for {text[:40]!r}"
        # and the public path (which routes through native) round-trips
        assert tok.decode(tok.encode_ordinary(text)) == text
