"""Cross-framework parity: our reference-architecture mode vs an independent
PyTorch implementation of the SURVEY §2.5 spec.

The torch model below is written from the architectural spec (pre-LN, per-head
biasless QKV, NO attention output projection, ReLU MLP with biases, learned
absolute positions, untied lm_head WITH bias, flat cross-entropy) — not copied
from the reference — and loaded with our initialized weights. Logits and loss
must agree to fp32 tolerance, which pins the `reference-3b` architecture flags
to the reference's actual semantics.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from pretraining_llm_tpu.config import ModelConfig
from pretraining_llm_tpu.models import transformer

CFG = ModelConfig(
    vocab_size=97,
    context_length=24,
    d_model=32,
    n_heads=4,
    n_layers=3,
    activation="relu",
    norm="layernorm",
    pos_embed="learned",
    use_output_proj=False,
    tie_embeddings=False,
    lm_head_bias=True,
    qkv_bias=False,
    mlp_bias=True,
    compute_dtype="float32",
)


class TorchRefModel(torch.nn.Module):
    """Reference-architecture decoder written from the spec (SURVEY §2.5)."""

    def __init__(self, cfg: ModelConfig):
        super().__init__()
        d, h, dh, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
        self.cfg = cfg
        self.tok = torch.nn.Embedding(cfg.vocab_size, d)
        self.pos = torch.nn.Embedding(cfg.context_length, d)
        self.blocks = torch.nn.ModuleList()
        for _ in range(cfg.n_layers):
            blk = torch.nn.ModuleDict(
                {
                    "ln1": torch.nn.LayerNorm(d, eps=cfg.norm_eps),
                    "ln2": torch.nn.LayerNorm(d, eps=cfg.norm_eps),
                    "qkv": torch.nn.ModuleList(
                        [
                            torch.nn.ModuleDict(
                                {
                                    "q": torch.nn.Linear(d, dh, bias=False),
                                    "k": torch.nn.Linear(d, dh, bias=False),
                                    "v": torch.nn.Linear(d, dh, bias=False),
                                }
                            )
                            for _ in range(h)
                        ]
                    ),
                    "fc1": torch.nn.Linear(d, f, bias=True),
                    "fc2": torch.nn.Linear(f, d, bias=True),
                }
            )
            self.blocks.append(blk)
        self.ln_f = torch.nn.LayerNorm(d, eps=cfg.norm_eps)
        self.head = torch.nn.Linear(d, cfg.vocab_size, bias=True)

    def forward(self, idx, targets=None):
        b, t = idx.shape
        x = self.tok(idx) + self.pos(torch.arange(t))[None]
        mask = torch.tril(torch.ones(t, t, dtype=torch.bool))
        for blk in self.blocks:
            hsrc = blk["ln1"](x)
            outs = []
            for head in blk["qkv"]:
                q, k, v = head["q"](hsrc), head["k"](hsrc), head["v"](hsrc)
                att = (q @ k.transpose(-2, -1)) / (q.shape[-1] ** 0.5)
                att = att.masked_fill(~mask, float("-inf"))
                outs.append(torch.softmax(att, dim=-1) @ v)
            x = x + torch.cat(outs, dim=-1)  # no output projection
            x = x + blk["fc2"](torch.relu(blk["fc1"](blk["ln2"](x))))
        x = self.ln_f(x)
        logits = self.head(x)
        loss = None
        if targets is not None:
            loss = torch.nn.functional.cross_entropy(
                logits.reshape(-1, logits.shape[-1]), targets.reshape(-1)
            )
        return logits, loss


def _load_our_params_into_torch(params, model: TorchRefModel, cfg: ModelConfig):
    p = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
    with torch.no_grad():
        model.tok.weight.copy_(torch.from_numpy(p["tok_embed"]["embedding"]))
        model.pos.weight.copy_(torch.from_numpy(p["pos_embed"]["embedding"]))
        for layer_index, blk in enumerate(model.blocks):
            bp = jax.tree.map(lambda a: a[layer_index], p["blocks"])
            blk["ln1"].weight.copy_(torch.from_numpy(bp["ln1"]["scale"]))
            blk["ln1"].bias.copy_(torch.from_numpy(bp["ln1"]["bias"]))
            blk["ln2"].weight.copy_(torch.from_numpy(bp["ln2"]["scale"]))
            blk["ln2"].bias.copy_(torch.from_numpy(bp["ln2"]["bias"]))
            wqkv = bp["attn"]["wqkv"]  # (D, 3, H, Dh)
            for h_index, head in enumerate(blk["qkv"]):
                head["q"].weight.copy_(torch.from_numpy(wqkv[:, 0, h_index].T))
                head["k"].weight.copy_(torch.from_numpy(wqkv[:, 1, h_index].T))
                head["v"].weight.copy_(torch.from_numpy(wqkv[:, 2, h_index].T))
            blk["fc1"].weight.copy_(torch.from_numpy(bp["mlp"]["w1"].T))
            blk["fc1"].bias.copy_(torch.from_numpy(bp["mlp"]["b1"]))
            blk["fc2"].weight.copy_(torch.from_numpy(bp["mlp"]["w2"].T))
            blk["fc2"].bias.copy_(torch.from_numpy(bp["mlp"]["b2"]))
        model.ln_f.weight.copy_(torch.from_numpy(p["final_norm"]["scale"]))
        model.ln_f.bias.copy_(torch.from_numpy(p["final_norm"]["bias"]))
        model.head.weight.copy_(torch.from_numpy(p["lm_head"]["kernel"].T))
        model.head.bias.copy_(torch.from_numpy(p["lm_head"]["bias"]))


def test_logits_and_loss_match_torch_reference_architecture():
    params = transformer.init_params(CFG, jax.random.key(0))
    model = TorchRefModel(CFG)
    _load_our_params_into_torch(params, model, CFG)

    tokens = np.asarray(
        jax.random.randint(jax.random.key(1), (2, CFG.context_length), 0, CFG.vocab_size)
    )
    targets = np.roll(tokens, -1, axis=1)

    ours_logits, _ = transformer.forward(params, jnp.asarray(tokens), CFG)
    ours_loss = transformer.loss_fn(
        params, jnp.asarray(tokens), jnp.asarray(targets), CFG
    )

    with torch.no_grad():
        torch_logits, torch_loss = model(
            torch.from_numpy(tokens).long(), torch.from_numpy(targets).long()
        )

    np.testing.assert_allclose(
        np.asarray(ours_logits), torch_logits.numpy(), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(float(ours_loss), float(torch_loss), rtol=1e-5)


def test_gpt2_mode_matches_torch_multihead():
    """Standard mode (fused QKV + output projection) vs torch MultiheadAttention-
    style math written independently."""
    cfg = dataclasses.replace(
        CFG, use_output_proj=True, tie_embeddings=True, lm_head_bias=False,
        activation="gelu", qkv_bias=True,
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    tokens = np.asarray(
        jax.random.randint(jax.random.key(1), (2, cfg.context_length), 0, cfg.vocab_size)
    )
    ours_logits, _ = transformer.forward(params, jnp.asarray(tokens), cfg)

    p = jax.tree.map(lambda a: np.asarray(a, np.float32), params)
    x = p["tok_embed"]["embedding"][tokens] + p["pos_embed"]["embedding"][None, : cfg.context_length]
    xt = torch.from_numpy(x)
    t = cfg.context_length
    mask = torch.tril(torch.ones(t, t, dtype=torch.bool))
    for li in range(cfg.n_layers):
        bp = jax.tree.map(lambda a: a[li], p["blocks"])
        h = torch.nn.functional.layer_norm(
            xt, (cfg.d_model,),
            torch.from_numpy(bp["ln1"]["scale"]), torch.from_numpy(bp["ln1"]["bias"]),
            eps=cfg.norm_eps,
        )
        qkv = torch.einsum("btd,dchn->bcthn", h, torch.from_numpy(bp["attn"]["wqkv"]))
        qkv = qkv + torch.from_numpy(bp["attn"]["bqkv"])[None, :, None]
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        att = torch.einsum("bqhd,bkhd->bhqk", q, k) / (cfg.head_dim**0.5)
        att = att.masked_fill(~mask[None, None], float("-inf"))
        out = torch.einsum("bhqk,bkhd->bqhd", torch.softmax(att, -1), v)
        out = torch.einsum("bthn,hnd->btd", out, torch.from_numpy(bp["attn"]["wo"]))
        xt = xt + out + torch.from_numpy(bp["attn"]["bo"])
        h = torch.nn.functional.layer_norm(
            xt, (cfg.d_model,),
            torch.from_numpy(bp["ln2"]["scale"]), torch.from_numpy(bp["ln2"]["bias"]),
            eps=cfg.norm_eps,
        )
        hidden = torch.nn.functional.gelu(
            h @ torch.from_numpy(bp["mlp"]["w1"]) + torch.from_numpy(bp["mlp"]["b1"]),
            approximate="tanh",
        )
        xt = xt + hidden @ torch.from_numpy(bp["mlp"]["w2"]) + torch.from_numpy(bp["mlp"]["b2"])
    xt = torch.nn.functional.layer_norm(
        xt, (cfg.d_model,),
        torch.from_numpy(p["final_norm"]["scale"]), torch.from_numpy(p["final_norm"]["bias"]),
        eps=cfg.norm_eps,
    )
    want = xt @ torch.from_numpy(p["tok_embed"]["embedding"]).T
    np.testing.assert_allclose(
        np.asarray(ours_logits), want.numpy(), rtol=2e-4, atol=2e-4
    )
