"""Training runtime end-to-end: loss decreases, sharding invariance,
microbatch equivalence, checkpoint/resume exactness."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.data import loader
from pretraining_llm_tpu.training import checkpoint as ckpt
from pretraining_llm_tpu.training import train_step as ts
from pretraining_llm_tpu.training.metrics import MetricsLogger
from pretraining_llm_tpu.training.trainer import Trainer


def _tiny_config(**train_kw):
    cfg = get_preset("tiny")
    train_kw.setdefault("checkpoint_interval", 0)
    train_kw.setdefault("eval_interval", 0)
    train_kw.setdefault("log_interval", 1000)
    return cfg.replace(train=dataclasses.replace(cfg.train, **train_kw))


def _batch(cfg, seed=0):
    it = loader.synthetic_iterator(
        cfg.model.vocab_size, cfg.model.context_length, cfg.train.batch_size, seed
    )
    return it


def test_loss_decreases_single_device(tmp_path):
    cfg = _tiny_config(train_steps=100, lr=3e-3, checkpoint_dir=str(tmp_path / "ck"))
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, mesh=None)
    it = _batch(cfg)
    first = None
    for i in range(100):
        x, y = next(it)
        state, metrics = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert first > 5.0  # ~ln(256)
    assert last < first - 1.0, (first, last)


def test_bf16_grad_dtype_trains_and_tracks_fp32():
    """grad_dtype='bfloat16' (the 1B HBM lever): training still learns,
    and a single step's parameter update stays close to the fp32-grad
    update (the knob narrows STORAGE; optimizer math reduces in fp32)."""
    cfg32 = _tiny_config(train_steps=5, lr=1e-3)
    cfg16 = _tiny_config(train_steps=5, lr=1e-3, grad_dtype="bfloat16")
    state32 = ts.init_train_state(cfg32, jax.random.key(0))
    state16 = jax.tree.map(jnp.copy, state32)
    it = _batch(cfg32)
    x, y = next(it)
    b = (jnp.asarray(x), jnp.asarray(y))
    new32, m32 = ts.build_train_step(cfg32, mesh=None)(state32, b)
    new16, m16 = ts.build_train_step(cfg16, mesh=None)(state16, b)
    # Same forward -> same loss to bf16 tolerance.
    assert abs(float(m32["loss"]) - float(m16["loss"])) < 0.05
    # The full update vectors point the same way. (Per-coordinate
    # comparison is ill-posed here: Adam's first step is sign-like, so a
    # bf16-noise grad flip on a near-zero coordinate moves it by a full
    # 2*lr — cosine over the whole update is the storage-narrowing claim.)
    params0 = ts.init_train_state(cfg32, jax.random.key(0))["params"]
    u32 = np.concatenate([
        (np.asarray(a, np.float32) - np.asarray(c, np.float32)).ravel()
        for a, c in zip(jax.tree.leaves(new32["params"]), jax.tree.leaves(params0))
    ])
    u16 = np.concatenate([
        (np.asarray(b, np.float32) - np.asarray(c, np.float32)).ravel()
        for b, c in zip(jax.tree.leaves(new16["params"]), jax.tree.leaves(params0))
    ])
    cos = float(u32 @ u16 / (np.linalg.norm(u32) * np.linalg.norm(u16) + 1e-12))
    assert cos > 0.8, cos
    # And it actually LEARNS over a few steps.
    cfg = _tiny_config(train_steps=30, lr=3e-3, grad_dtype="bfloat16")
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, mesh=None)
    it = _batch(cfg)
    first = None
    for _ in range(30):
        x, y = next(it)
        state, metrics = step(state, (jnp.asarray(x), jnp.asarray(y)))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first - 0.3


def test_bf16_grad_dtype_microbatch_accumulator():
    """The accumulation path under grad_dtype='bfloat16' runs and learns
    (the accumulator itself stores bf16 — the documented trade)."""
    cfg = _tiny_config(
        train_steps=5, lr=1e-3, microbatches=4, grad_dtype="bfloat16"
    )
    state = ts.init_train_state(cfg, jax.random.key(0))
    step = ts.build_train_step(cfg, mesh=None)
    it = _batch(cfg)
    for _ in range(5):
        x, y = next(it)
        state, metrics = step(state, (jnp.asarray(x), jnp.asarray(y)))
    assert np.isfinite(float(metrics["loss"]))


def test_microbatch_accumulation_matches_full_batch():
    # fp32 compute so the only difference is the accumulation structure
    # (bf16 reduction-order noise would otherwise dominate the comparison).
    cfg1 = _tiny_config(train_steps=5, microbatches=1, grad_clip=0.0)
    cfg2 = _tiny_config(train_steps=5, microbatches=4, grad_clip=0.0)
    cfg1 = cfg1.with_overrides({"model.compute_dtype": "float32"})
    cfg2 = cfg2.with_overrides({"model.compute_dtype": "float32"})
    state1 = ts.init_train_state(cfg1, jax.random.key(0))
    state2 = ts.init_train_state(cfg2, jax.random.key(0))
    step1 = ts.build_train_step(cfg1, mesh=None)
    step2 = ts.build_train_step(cfg2, mesh=None)
    it = _batch(cfg1)
    for _ in range(3):
        x, y = next(it)
        batch = (jnp.asarray(x), jnp.asarray(y))
        state1, m1 = step1(state1, batch)
        state2, m2 = step2(state2, batch)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=2e-4, atol=2e-5
        ),
        state1["params"],
        state2["params"],
    )


def test_sharding_invariance(mesh8):
    """Same batch, same init: 8-device sharded step == single-device step."""
    cfg = _tiny_config(train_steps=3, batch_size=8).with_overrides(
        {"model.compute_dtype": "float32"}
    )
    state_a = ts.init_train_state(cfg, jax.random.key(0))
    state_b = ts.init_train_state(cfg, jax.random.key(0))
    step_single = ts.build_train_step(cfg, mesh=None)
    step_mesh = ts.build_train_step(cfg, mesh=mesh8)
    state_b = ts.shard_train_state(state_b, mesh8)
    it = _batch(cfg)
    for _ in range(3):
        x, y = next(it)
        batch = (jnp.asarray(x), jnp.asarray(y))
        state_a, ma = step_single(state_a, batch)
        state_b, mb = step_mesh(state_b, batch)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=1e-3, atol=1e-4
        ),
        state_a["params"],
        state_b["params"],
    )


def test_sharding_invariance_dense_ce(mesh8):
    """ce_impl='dense' (saved-logits head): 8-device sharded step == single
    device — the custom VJP's einsums and the (B,T)->(S,) reshape must
    compose through GSPMD exactly like the chunked scan does."""
    cfg = _tiny_config(train_steps=2, batch_size=8).with_overrides(
        {"model.compute_dtype": "float32", "model.ce_impl": "dense"}
    )
    state_a = ts.init_train_state(cfg, jax.random.key(0))
    state_b = ts.init_train_state(cfg, jax.random.key(0))
    step_single = ts.build_train_step(cfg, mesh=None)
    step_mesh = ts.build_train_step(cfg, mesh=mesh8)
    state_b = ts.shard_train_state(state_b, mesh8)
    it = _batch(cfg)
    for _ in range(2):
        x, y = next(it)
        batch = (jnp.asarray(x), jnp.asarray(y))
        state_a, ma = step_single(state_a, batch)
        state_b, mb = step_mesh(state_b, batch)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=1e-3, atol=1e-4
        ),
        state_a["params"],
        state_b["params"],
    )


def test_fsdp_actually_shards_params(mesh8):
    cfg = _tiny_config()
    state = ts.init_train_state(cfg, jax.random.key(0))
    state = ts.shard_train_state(state, mesh8)
    w1 = state["params"]["blocks"]["mlp"]["w1"]  # (L, D, F) spec (None,'fsdp','tensor')
    shard_shape = w1.sharding.shard_shape(w1.shape)
    assert shard_shape[1] == w1.shape[1] // 2  # fsdp axis size 2
    assert shard_shape[2] == w1.shape[2] // 2  # tensor axis size 2
    # Optimizer moments shard identically
    mu = state["opt"]["mu"]["blocks"]["mlp"]["w1"]
    assert mu.sharding == w1.sharding


def test_checkpoint_roundtrip_and_exact_resume(tmp_path):
    ckdir = str(tmp_path / "ck")
    cfg = _tiny_config(train_steps=10, checkpoint_interval=5, checkpoint_dir=ckdir, lr=1e-3)

    logger = MetricsLogger()
    t1 = Trainer(cfg, synthetic_data=True, resume=False, logger=logger)
    t1.train()
    final_a = jax.device_get(t1.state["params"])

    # Second trainer resumes from step 5's checkpoint and must reproduce the
    # exact same final params (same data order via saved RNG state).
    latest = ckpt.latest_checkpoint(ckdir)
    assert latest is not None and latest.endswith("step-10")
    # Remove the last checkpoint so resume starts from step 5.
    import shutil

    shutil.rmtree(latest)
    t2 = Trainer(cfg, synthetic_data=True, resume=True, logger=logger)
    assert t2.start_step == 5
    t2.train()
    final_b = jax.device_get(t2.state["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        final_a,
        final_b,
    )


def test_checkpoint_shape_mismatch_rejected(tmp_path):
    cfg = _tiny_config()
    state = ts.init_train_state(cfg, jax.random.key(0))
    path = ckpt.save_checkpoint(str(tmp_path), 1, state)
    bigger = get_preset("tiny").with_overrides({"model.d_model": 64})
    template = ts.init_train_state(bigger, jax.random.key(0))
    with pytest.raises(ValueError, match="shape"):
        ckpt.load_checkpoint(path, template)


def test_checkpoint_retention(tmp_path):
    cfg = _tiny_config()
    state = ts.init_train_state(cfg, jax.random.key(0))
    for s in (1, 2, 3, 4):
        ckpt.save_checkpoint(str(tmp_path), s, state, keep=2)
    steps = ckpt._list_steps(str(tmp_path))
    assert sorted(steps) == [3, 4]


def test_trainer_eval_and_metrics(tmp_path, capsys):
    cfg = _tiny_config(
        train_steps=6,
        eval_interval=3,
        eval_iters=2,
        log_interval=2,
        checkpoint_interval=0,
        checkpoint_dir=str(tmp_path / "ck"),
        metrics_path=str(tmp_path / "m.jsonl"),
    )
    t = Trainer(cfg, synthetic_data=True, resume=False)
    last = t.train()
    assert "loss" in last and "val_loss" in last
    import json

    records = [json.loads(line) for line in open(tmp_path / "m.jsonl")]
    assert any("val_loss" in r for r in records)
    assert any("tokens_per_sec" in r for r in records)


def test_eval_deterministic_across_calls_and_training(tmp_path):
    """evaluate() uses a fixed seeded eval set: identical loss on repeated
    calls, and unaffected by how far training has advanced the train stream."""
    cfg = _tiny_config(
        train_steps=2,
        eval_iters=3,
        checkpoint_interval=0,
        checkpoint_dir=str(tmp_path / "ck"),
    )
    t = Trainer(cfg, synthetic_data=True, resume=False)
    v1 = t.evaluate()
    v2 = t.evaluate()
    assert v1 == v2  # bit-identical: same batches, same one-dispatch program
    t.train(steps=2)
    t2 = Trainer(cfg, synthetic_data=True, resume=False)
    # Fresh trainer, same config: same eval batches (params differ, so only
    # check the batch stream by re-evaluating the ORIGINAL params' loss).
    assert t2.evaluate() == v1


def test_checkpoint_sharded_leaf_reassembly(tmp_path):
    """Multi-host shard file format: split leaves reassemble exactly."""
    import json as _json

    from pretraining_llm_tpu.training.checkpoint import _load_leaf

    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    # Simulate two processes each writing half of the rows.
    for p, sl in enumerate([slice(0, 2), slice(2, 4)]):
        np.save(tmp_path / f"w.p{p}_0.npy", arr[sl])
        (tmp_path / f"w.p{p}_0.npy.idx").write_text(
            _json.dumps([[sl.start, sl.stop], [0, 6]])
        )
    entry = {"name": "w", "shape": [4, 6], "dtype": "float32", "sharded": True}
    got = _load_leaf(str(tmp_path), entry)
    np.testing.assert_array_equal(got, arr)


def test_checkpoint_load_with_eval_shape_template(tmp_path):
    cfg = _tiny_config()
    state = ts.init_train_state(cfg, jax.random.key(0))
    path = ckpt.save_checkpoint(str(tmp_path), 1, state)
    template = jax.eval_shape(lambda: ts.init_train_state(cfg, jax.random.key(0)))
    restored, _ = ckpt.load_checkpoint(path, template)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        jax.device_get(state),
        restored,
    )


def test_loader_minimum_size_shard():
    """A shard of exactly context_length+1 tokens has one valid crop."""
    import numpy as _np

    from pretraining_llm_tpu.data.loader import BatchIterator, MemmapTokens

    class _Mini:
        data = _np.arange(17, dtype=_np.uint16)
        context_length = 16
        sample_batch = MemmapTokens.sample_batch

    it = BatchIterator(_Mini(), batch_size=4, seed=0)
    x, y = next(it)
    _np.testing.assert_array_equal(x, _np.tile(_np.arange(16), (4, 1)))
    _np.testing.assert_array_equal(y, _np.tile(_np.arange(1, 17), (4, 1)))


def test_lower_train_step_memory_analysis():
    """The AOT preflight lowers/compiles from shape specs alone and exposes
    a readable memory analysis (scripts/train.py --compile-only contract)."""
    cfg = _tiny_config(train_steps=1)
    compiled = ts.lower_train_step(cfg, mesh=None).compile()
    mem = compiled.memory_analysis()
    assert mem.temp_size_in_bytes >= 0
    assert mem.argument_size_in_bytes >= 0


def test_prefetch_matches_synchronous_loop(tmp_path):
    """The prefetch feed changes WHEN batches are staged, never WHICH:
    per-step losses with data.prefetch=2 equal the prefetch=0 loop."""
    runs = {}
    for depth in (0, 2):
        cfg = _tiny_config(
            train_steps=6, log_interval=1, checkpoint_dir=str(tmp_path / f"p{depth}")
        )
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, prefetch=depth))
        losses = []

        class _Capture:
            def log(self, rec):
                if "loss" in rec:
                    losses.append(float(rec["loss"]))

        t = Trainer(cfg, synthetic_data=True, resume=False, logger=_Capture())
        t.train()
        runs[depth] = losses
        # The feed is closed (and the source rewound to the consumed
        # frontier) on exit either way; the iterator's state must equal the
        # synchronous run's — 6 batches consumed exactly.
        assert t._feed is None
    assert runs[0] == runs[2], (runs[0], runs[2])


def test_incremental_training_with_prefetch_matches_straight_run(tmp_path):
    """train(3) then train(6) on one Trainer == train(6) straight: closing
    the feed at each train() exit rewinds the source to the consumed
    frontier, so the second call's fresh feed re-draws the queued batches."""
    def make(tag):
        cfg = _tiny_config(
            train_steps=6, log_interval=1, checkpoint_dir=str(tmp_path / tag)
        )
        losses = []

        class _Cap:
            def log(self, rec):
                if "loss" in rec:
                    losses.append(round(float(rec["loss"]), 6))

        return Trainer(cfg, synthetic_data=True, resume=False, logger=_Cap()), losses

    t1, l1 = make("straight")
    t1.train(steps=6)

    t2, l2 = make("split")
    t2.train(steps=3)
    assert t2._feed is None  # closed + rewound between calls
    t2.train(steps=3)  # train(steps=N) runs N further steps
    assert l2 == l1, (l2, l1)
