"""Ulysses all-to-all sequence parallelism vs dense attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import get_preset
from pretraining_llm_tpu.ops.attention import naive_attention
from pretraining_llm_tpu.parallel.ulysses import ulysses_attention
from pretraining_llm_tpu.training import train_step as ts


def _qkv(key, b=2, t=64, h=4, dh=16):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, t, h, dh), jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(mesh_seq4, causal):
    q, k, v = _qkv(jax.random.key(0))  # 4 heads, seq axis 4
    want = naive_attention(q, k, v, causal=causal)

    @jax.jit
    def run(q, k, v):
        return ulysses_attention(q, k, v, mesh_seq4, causal=causal)

    got = run(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_ulysses_gradients_match_dense(mesh_seq4):
    q, k, v = _qkv(jax.random.key(1), t=32)
    g_dense = jax.grad(lambda *a: jnp.sum(naive_attention(*a) ** 2), (0, 1, 2))(q, k, v)

    @jax.jit
    def g_uly(q, k, v):
        return jax.grad(
            lambda *a: jnp.sum(ulysses_attention(*a, mesh_seq4) ** 2), (0, 1, 2)
        )(q, k, v)

    for a, b in zip(g_dense, g_uly(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_ulysses_rejects_indivisible_heads(mesh_seq4):
    q, k, v = _qkv(jax.random.key(2), h=3)  # 3 heads on a seq=4 axis
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh_seq4)


def test_ulysses_train_step_matches_dense(mesh_seq4):
    cfg = get_preset("tiny").with_overrides(
        {
            "model.compute_dtype": "float32",
            "model.attention_impl": "ulysses",
            "model.sequence_parallel": True,
            "train.batch_size": 4,
            "train.checkpoint_interval": 0,
            "train.eval_interval": 0,
        }
    )
    cfg_dense = cfg.with_overrides(
        {"model.attention_impl": "naive", "model.sequence_parallel": False}
    )
    state_u = ts.init_train_state(cfg, jax.random.key(0))
    state_d = ts.init_train_state(cfg_dense, jax.random.key(0))
    step_u = ts.build_train_step(cfg, mesh=mesh_seq4)
    step_d = ts.build_train_step(cfg_dense, mesh=None)
    state_u = ts.shard_train_state(state_u, mesh_seq4)
    x = jax.random.randint(jax.random.key(1), (4, cfg.model.context_length), 0, cfg.model.vocab_size)
    y = jnp.roll(x, -1, axis=1)
    state_u, mu = step_u(state_u, (x, y))
    state_d, md = step_d(state_d, (x, y))
    np.testing.assert_allclose(float(mu["loss"]), float(md["loss"]), rtol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_gqa_matches_grouped_dense(mesh_seq4, causal):
    """Grouped KV rides the all-to-all un-expanded (G/H the bytes); the
    contiguous head split is group-aligned so the inner grouped kernel sees
    whole groups. G=4 divides the seq axis (4)."""
    b, t, h, g, dh = 2, 64, 8, 4, 16
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, t, h, dh), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, g, dh), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, g, dh), jnp.float32)
    want = naive_attention(q, k, v, causal=causal)

    @jax.jit
    def run(q, k, v):
        return ulysses_attention(q, k, v, mesh_seq4, causal=causal)

    np.testing.assert_allclose(
        np.asarray(run(q, k, v)), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_ulysses_gqa_gradients_match_grouped_dense(mesh_seq4):
    b, t, h, g, dh = 2, 32, 8, 4, 8
    ks = jax.random.split(jax.random.key(8), 3)
    q = jax.random.normal(ks[0], (b, t, h, dh), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, g, dh), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, g, dh), jnp.float32)

    g_dense = jax.grad(lambda *a: jnp.sum(naive_attention(*a) ** 2), (0, 1, 2))(q, k, v)

    @jax.jit
    def u_grads(q, k, v):
        return jax.grad(
            lambda *a: jnp.sum(ulysses_attention(*a, mesh_seq4) ** 2), (0, 1, 2)
        )(q, k, v)

    for a, b_ in zip(g_dense, u_grads(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5)


def test_ulysses_supports_grouped_predicate(mesh_seq4):
    from pretraining_llm_tpu.parallel.ulysses import ulysses_supports_grouped

    # seq=4: G=4 splits evenly, G=2 does not (dispatch must expand KV).
    assert ulysses_supports_grouped(mesh_seq4, 8, 4)
    assert not ulysses_supports_grouped(mesh_seq4, 8, 2)
    assert ulysses_supports_grouped(None, 8, 2)  # no mesh -> naive fallback
