"""z-loss (model.z_loss_coef): value + gradients vs a dense autodiff
reference, for both custom-VJP CE heads."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pretraining_llm_tpu.config import ModelConfig, get_preset
from pretraining_llm_tpu.models import transformer

Z = 1e-3


def _ref_loss(params, toks, targets, cfg):
    """Plain autodiff reference: CE + z * mean(lse^2) over full logits."""
    logits, _ = transformer.forward(params, toks, cfg)
    logits = logits.astype(jnp.float32)
    b, t, v = logits.shape
    flat = logits.reshape(b * t, v)
    lse = jax.nn.logsumexp(flat, axis=-1)
    label = jnp.take_along_axis(flat, targets.reshape(-1)[:, None], axis=-1)[:, 0]
    return jnp.mean(lse - label) + Z * jnp.mean(jnp.square(lse))


@pytest.mark.parametrize("ce_impl", ["chunked", "dense"])
def test_z_loss_value_and_grads_match_reference(ce_impl):
    cfg = dataclasses.replace(
        get_preset("tiny").model, compute_dtype="float32",
        ce_impl=ce_impl, z_loss_coef=Z,
    )
    params = transformer.init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, cfg.context_length),
                              0, cfg.vocab_size)
    targets = jnp.roll(toks, -1, axis=1)

    got, got_g = jax.value_and_grad(
        lambda p: transformer.loss_fn(p, toks, targets, cfg)
    )(params)
    want, want_g = jax.value_and_grad(
        lambda p: _ref_loss(p, toks, targets, cfg)
    )(params)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(got_g), jax.tree.leaves(want_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=1e-3)


def test_z_loss_changes_the_objective():
    cfg0 = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
    cfgz = dataclasses.replace(cfg0, z_loss_coef=1e-2)
    params = transformer.init_params(cfg0, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (1, cfg0.context_length),
                              0, cfg0.vocab_size)
    targets = jnp.roll(toks, -1, axis=1)
    l0 = float(transformer.loss_fn(params, toks, targets, cfg0))
    lz = float(transformer.loss_fn(params, toks, targets, cfgz))
    assert lz > l0  # lse^2 is positive at init


def test_z_loss_validation():
    with pytest.raises(ValueError, match=">= 0"):
        ModelConfig(z_loss_coef=-0.1)
    with pytest.raises(ValueError, match="fused"):
        ModelConfig(z_loss_coef=1e-3, ce_impl="fused")


def test_z_loss_excluded_from_eval():
    """include_aux=False (the eval path) reports PURE cross-entropy."""
    cfg0 = dataclasses.replace(get_preset("tiny").model, compute_dtype="float32")
    cfgz = dataclasses.replace(cfg0, z_loss_coef=1e-2)
    params = transformer.init_params(cfg0, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (1, cfg0.context_length),
                              0, cfg0.vocab_size)
    targets = jnp.roll(toks, -1, axis=1)
    pure = float(transformer.loss_fn(params, toks, targets, cfg0,
                                     include_aux=False))
    with_z_eval = float(transformer.loss_fn(params, toks, targets, cfgz,
                                            include_aux=False))
    assert with_z_eval == pure


def test_z_loss_multi_chunk_scan_matches_dense_head():
    """The chunked head's z accumulation across an ACTUAL multi-chunk scan
    (forward sum + backward rescale per chunk) must equal the dense head."""
    from pretraining_llm_tpu.models.transformer import (
        _dense_lse_ce, _lse_saved_ce,
    )

    s, d, v, z = 64, 16, 97, 1e-2
    x = jax.random.normal(jax.random.key(2), (s, d), jnp.float32)
    w = jax.random.normal(jax.random.key(3), (d, v), jnp.float32) * 0.1
    ts_ = jax.random.randint(jax.random.key(4), (s,), 0, v)

    def chunked(x, w):
        return _lse_saved_ce(
            x.reshape(4, s // 4, d), w, None, ts_.reshape(4, s // 4),
            jnp.float32, z=z,
        )

    def dense(x, w):
        return _dense_lse_ce(x, w, None, ts_, jnp.float32, z=z)

    (vc, gc), (vd, gd) = (
        jax.value_and_grad(chunked, (0, 1))(x, w),
        jax.value_and_grad(dense, (0, 1))(x, w),
    )
    np.testing.assert_allclose(float(vc), float(vd), rtol=1e-6)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)
